"""Real-xgboost adapter tests (VERDICT r2 'do this' #6).

xgboost is not installed in this environment (SURVEY.md §2.1), so the
suite covers the adapter three ways:

- pure translation/selection logic (no xgboost needed);
- the full ``xgb.cv`` call contract through a recording fake module
  (asserts exactly what a real xgboost would receive);
- real end-to-end runs guarded by ``pytest.importorskip`` — skipped here,
  green on any machine with xgboost installed.
"""

import sys
import types

import numpy as np
import pytest

from gentun_tpu import XgboostIndividual
from gentun_tpu.genes import xgboost_genome
from gentun_tpu.models import default_boosting_model
from gentun_tpu.models.boosting import BoostingModel
from gentun_tpu.models.xgboost import (
    XgboostModel,
    genes_to_xgb_params,
    xgboost_available,
)


def reference_genes():
    """One value per reference gene (gentun XgboostIndividual [PUB])."""
    return {
        "eta": 0.3, "min_child_weight": 2, "max_depth": 7, "gamma": 0.5,
        "max_delta_step": 1, "subsample": 0.9, "colsample_bytree": 0.8,
        "colsample_bylevel": 0.7, "lambda": 1.5, "alpha": 0.2,
        "scale_pos_weight": 3.0,
    }


class TestGeneTranslation:
    def test_all_reference_genes_pass_through_live(self):
        """With a real xgboost backend, ALL 11 reference genes are live —
        the sklearn translation's inert-gene caveat is exactly what this
        adapter removes."""
        params = genes_to_xgb_params(reference_genes())
        assert set(params) == set(reference_genes())
        assert params["max_depth"] == 7 and isinstance(params["max_depth"], int)
        assert params["lambda"] == pytest.approx(1.5)

    def test_sklearn_names_translate(self):
        params = genes_to_xgb_params(
            {"learning_rate": 0.1, "l2_regularization": 2.0, "min_samples_leaf": 5,
             "max_bins": 64, "max_iter": 50}
        )
        assert params["eta"] == pytest.approx(0.1)
        assert params["lambda"] == pytest.approx(2.0)
        assert params["min_child_weight"] == pytest.approx(5.0)
        assert params["max_bin"] == 64
        assert "max_iter" not in params  # control gene → num_boost_round

    def test_max_leaf_nodes_enables_lossguide(self):
        params = genes_to_xgb_params({"max_leaf_nodes": 31})
        assert params["max_leaves"] == 31
        assert params["grow_policy"] == "lossguide"
        assert params["tree_method"] == "hist"

    def test_unknown_gene_raises(self):
        with pytest.raises(ValueError, match="no xgboost mapping"):
            genes_to_xgb_params({"mystery_knob": 1})


class TestBackendSelection:
    def test_fallback_chain_in_this_environment(self):
        """No xgboost here → sklearn backend; with xgboost → the adapter."""
        if xgboost_available():  # pragma: no cover - env-dependent
            assert default_boosting_model() is XgboostModel
        else:
            assert default_boosting_model() is BoostingModel

    def test_xgboost_individual_searches_reference_genome(self):
        ind = XgboostIndividual(
            x_train=None, y_train=None, additional_parameters={}
        )
        spec = xgboost_genome()
        assert set(ind.get_genes()) == {g.name for g in spec.genes}
        assert len(ind.get_genes()) == 11


class _FakeXgboost(types.ModuleType):
    """Records the cv() call and returns a canned cv table."""

    def __init__(self):
        super().__init__("xgboost")
        self.cv_calls = []

    class DMatrix:
        def __init__(self, data, label=None):
            self.data = np.asarray(data)
            self.label = np.asarray(label)

    def cv(self, params, dtrain, **kwargs):
        self.cv_calls.append({"params": params, "dtrain": dtrain, **kwargs})
        metric = kwargs["metrics"][0]
        # xgb.cv returns a table; the adapter reads the LAST row of
        # test-<metric>-mean (early stopping truncates the table there).
        return {f"test-{metric}-mean": [0.5, 0.3, 0.25]}


class TestCvCallContract:
    """Drives XgboostModel through a fake xgboost module and asserts the
    exact call a real xgboost would receive."""

    @pytest.fixture
    def fake_xgb(self, monkeypatch):
        fake = _FakeXgboost()
        monkeypatch.setitem(sys.modules, "xgboost", fake)
        xgboost_available.cache_clear()  # availability is lru-cached
        yield fake
        xgboost_available.cache_clear()

    def test_multiclass_accuracy(self, fake_xgb):
        x = np.random.default_rng(0).normal(size=(30, 4))
        y = np.array([7, 8, 9] * 10)  # non-contiguous labels
        model = XgboostModel(x, y, reference_genes(), kfold=3, seed=4)
        fitness = model.cross_validate()
        call = fake_xgb.cv_calls[-1]
        assert call["params"]["objective"] == "multi:softmax"
        assert call["params"]["num_class"] == 3
        assert call["params"]["eta"] == pytest.approx(0.3)
        assert call["nfold"] == 3
        assert call["metrics"] == ("merror",)
        assert call["stratified"] is True
        assert call["seed"] == 4
        assert call["early_stopping_rounds"] == 20
        assert set(np.unique(call["dtrain"].label)) == {0, 1, 2}  # remapped
        assert fitness == pytest.approx(1.0 - 0.25)  # accuracy = 1 - merror

    def test_binary_auc_and_regression_rmse(self, fake_xgb):
        rng = np.random.default_rng(1)
        x = rng.normal(size=(20, 3))
        yb = (rng.random(20) > 0.5).astype(int)
        auc = XgboostModel(x, yb, {"eta": 0.1}, kfold=2, metric="auc").cross_validate()
        assert fake_xgb.cv_calls[-1]["params"]["objective"] == "binary:logistic"
        assert fake_xgb.cv_calls[-1]["metrics"] == ("auc",)
        assert auc == pytest.approx(0.25)  # raw metric, no inversion

    def test_regression_and_early_stopping_off(self, fake_xgb):
        rng = np.random.default_rng(2)
        x, y = rng.normal(size=(20, 3)), rng.normal(size=20)
        rmse = XgboostModel(
            x, y, {"eta": 0.1, "max_iter": 77}, task="regression", early_stopping=False
        ).cross_validate()
        call = fake_xgb.cv_calls[-1]
        assert call["params"]["objective"] == "reg:squarederror"
        assert call["early_stopping_rounds"] is None
        assert call["num_boost_round"] == 77  # max_iter gene overrides
        assert call["stratified"] is False
        assert rmse == pytest.approx(0.25)

    def test_selection_picks_adapter_when_importable(self, fake_xgb):
        assert xgboost_available()
        assert default_boosting_model() is XgboostModel

    def test_invalid_config(self):
        x, y = np.zeros((4, 2)), np.zeros(4)
        with pytest.raises(ValueError):
            XgboostModel(x, y, {}, task="clustering")
        with pytest.raises(ValueError):
            XgboostModel(x, y, {}, task="regression", metric="accuracy")
        with pytest.raises(ValueError, match="rmse"):
            XgboostModel(x, np.array([0, 1, 0, 1]), {}, metric="rmse")
        with pytest.raises(ValueError, match="binary"):
            # auc + 3 classes must fail in the constructor, not inside xgb.cv
            XgboostModel(x, np.array([0, 1, 2, 0]), {}, metric="auc")


class TestRealXgboost:
    """Skipped in this environment; green wherever xgboost is installed."""

    def test_cv_on_wine(self):
        pytest.importorskip("xgboost")
        from gentun_tpu.utils.datasets import load_uci_wine

        x, y, _ = load_uci_wine()
        acc = XgboostModel(
            x, y, reference_genes(), kfold=3, num_boost_round=50
        ).cross_validate()
        assert 0.6 < acc <= 1.0

    def test_individual_end_to_end(self):
        pytest.importorskip("xgboost")
        from gentun_tpu.utils.datasets import load_uci_binary

        x, y, _ = load_uci_binary()
        ind = XgboostIndividual(
            x_train=x, y_train=y, additional_parameters={"kfold": 3}
        )
        assert 0.5 < ind.get_fitness() <= 1.0
