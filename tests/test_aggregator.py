"""Fleet metrics aggregation plane (telemetry/aggregator.py + slo.py).

The aggregator is a push gateway: every process ships CUMULATIVE
snapshots and the SERVER owns merge semantics.  These tests pin the
parts that guard fleet-sum correctness — counter resets after a process
restart (both the new-boot_id fold and the same-boot value drop), label
collisions across instances, late/out-of-order pushes — plus the
DeltaSnapshotter memoization the push-path micro-gate certifies, the
SLO engine's fire/self-clear/flap-damping state machine, the pusher's
ONE-degraded-event contract, and the wire guards (409 skew, exposition
grammar).
"""

import json
import re
import time
import urllib.error
import urllib.request

import pytest

from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.aggregator import (
    AGG_PROTOCOL,
    MetricsAggregator,
    TelemetryPusher,
    acquire_pusher,
    parse_aggregator_url,
    release_pusher,
)
from gentun_tpu.telemetry.buildinfo import build_info_labels
from gentun_tpu.telemetry.registry import (
    DeltaSnapshotter,
    MetricsRegistry,
    get_registry,
)
from gentun_tpu.telemetry.slo import (
    SeriesPoints,
    SloEngine,
    SloRule,
    default_rules,
)

# Prometheus text exposition grammar (the subset the registry and the
# aggregator emit) — same check scripts/ops_smoke.py runs.
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE+.\-]+(?: [0-9]+)?$')


class _ListSink:
    def __init__(self):
        self.records = []

    def record(self, rec):
        self.records.append(rec)


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _validate_prometheus(text):
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE_RE.match(line), f"invalid exposition line: {line!r}"


def _push(agg, instance, seq, counters=(), gauges=(), histograms=(),
          boot="boot-a", role="worker"):
    ok, detail = agg.push({
        "instance": instance, "role": role, "boot_id": boot, "seq": seq,
        "metrics": {"counters": list(counters), "gauges": list(gauges),
                    "histograms": list(histograms)},
    })
    assert ok, detail
    return detail


# ---------------------------------------------------------------------------
# DeltaSnapshotter: the memoization the ≤2% push-path gate certifies.


class TestDeltaSnapshotter:
    def test_first_collect_ships_everything_then_only_changes(self):
        reg = MetricsRegistry()
        reg.counter("jobs_total").inc(3)
        reg.gauge("depth").set(7)
        snap = DeltaSnapshotter(reg)

        first = snap.collect()
        assert {c["name"] for c in first["counters"]} == {"jobs_total"}
        assert {g["name"] for g in first["gauges"]} == {"depth"}

        # Nothing moved → nothing shipped.
        assert DeltaSnapshotterTotal(snap.collect()) == 0

        # Only the instrument that moved ships, with its FULL cumulative
        # value (the server diffs, the client never does).
        reg.counter("jobs_total").inc(2)
        delta = snap.collect()
        assert [c["name"] for c in delta["counters"]] == ["jobs_total"]
        assert delta["counters"][0]["value"] == 5.0
        assert delta["gauges"] == []

    def test_full_resends_unchanged_series(self):
        reg = MetricsRegistry()
        reg.counter("jobs_total").inc(1)
        snap = DeltaSnapshotter(reg)
        snap.collect()
        assert snap.collect()["counters"] == []
        assert [c["name"] for c in snap.collect(full=True)["counters"]] == [
            "jobs_total"]

    def test_histogram_keyed_on_count_and_sum(self):
        reg = MetricsRegistry()
        reg.histogram("lat_s").observe(0.5)
        snap = DeltaSnapshotter(reg)
        assert len(snap.collect()["histograms"]) == 1
        assert snap.collect()["histograms"] == []
        reg.histogram("lat_s").observe(0.25)
        hs = snap.collect()["histograms"]
        assert len(hs) == 1 and hs[0]["count"] == 2

    def test_label_sets_tracked_independently(self):
        reg = MetricsRegistry()
        reg.counter("hits", session="a").inc()
        reg.counter("hits", session="b").inc()
        snap = DeltaSnapshotter(reg)
        snap.collect()
        reg.counter("hits", session="b").inc()
        delta = snap.collect()["counters"]
        assert len(delta) == 1 and delta[0]["labels"] == {"session": "b"}


def DeltaSnapshotterTotal(snapshot):
    return sum(len(v) for v in snapshot.values())


# ---------------------------------------------------------------------------
# Merge semantics: resets, collisions, ordering.


class TestMergeSemantics:
    def test_counter_reset_same_boot_folds_into_base(self):
        # 100 then (restarted process reusing its boot file?) 5: the fleet
        # must read 105, never 5 and never backwards.
        agg = MetricsAggregator("127.0.0.1", 0)
        _push(agg, "w0", 1, counters=[{"name": "c", "labels": {}, "value": 100.0}])
        _push(agg, "w0", 2, counters=[{"name": "c", "labels": {}, "value": 5.0}])
        assert agg.stats()["resets_detected"] == 1
        assert agg.statusz()["fleet"]["counters"]["c"] == 105.0

    def test_boot_id_change_folds_all_cumulative_series(self):
        agg = MetricsAggregator("127.0.0.1", 0)
        _push(agg, "w0", 3, boot="life-1",
              counters=[{"name": "c", "labels": {}, "value": 100.0}])
        # New life: seq restarts from 1 and the counter restarts from 5.
        _push(agg, "w0", 1, boot="life-2",
              counters=[{"name": "c", "labels": {}, "value": 5.0}])
        assert agg.statusz()["fleet"]["counters"]["c"] == 105.0
        # Low seq was accepted because the boot changed.
        assert agg.stats()["pushes_dropped"] == 0

    def test_out_of_order_push_dropped(self):
        agg = MetricsAggregator("127.0.0.1", 0)
        _push(agg, "w0", 5, counters=[{"name": "c", "labels": {}, "value": 9.0}])
        detail = _push(agg, "w0", 3,
                       counters=[{"name": "c", "labels": {}, "value": 2.0}])
        assert detail.get("dropped")
        assert agg.stats()["pushes_dropped"] == 1
        # The stale snapshot never touched the series.
        assert agg.statusz()["fleet"]["counters"]["c"] == 9.0

    def test_label_collision_across_instances_sums_not_clobbers(self):
        # Two workers emit the identical (name, labels) series; the fleet
        # rollup must sum them and the exposition must keep them apart via
        # the injected instance label.
        agg = MetricsAggregator("127.0.0.1", 0)
        series = [{"name": "jobs_total", "labels": {"session": "s"}, "value": 4.0}]
        _push(agg, "w0", 1, counters=series)
        _push(agg, "w1", 1, counters=[{**series[0], "value": 6.0}])
        assert agg.statusz()["fleet"]["counters"]["jobs_total"] == 10.0
        text = agg.render_prometheus()
        assert 'instance="w0"' in text and 'instance="w1"' in text
        _validate_prometheus(text)

    def test_histogram_reset_does_not_double_count_buckets(self):
        agg = MetricsAggregator("127.0.0.1", 0)
        h = {"name": "lat_s", "labels": {}, "count": 10, "sum": 5.0,
             "buckets": [[1.0, 8.0], ["+Inf", 10.0]]}
        _push(agg, "w0", 1, histograms=[h])
        _push(agg, "w0", 2, histograms=[{**h, "count": 2, "sum": 1.0,
                                         "buckets": [[1.0, 1.0], ["+Inf", 2.0]]}])
        text = agg.render_prometheus()
        # count folded: 10 + 2; +Inf bucket likewise 10 + 2, not 10+10+2.
        assert re.search(r'lat_s_count\{[^}]*\} 12\b', text), text
        inf = [l for l in text.splitlines() if 'le="+Inf"' in l]
        assert inf and inf[0].rstrip().endswith(" 12"), inf

    def test_gauge_never_resets(self):
        agg = MetricsAggregator("127.0.0.1", 0)
        _push(agg, "w0", 1, gauges=[{"name": "depth", "labels": {}, "value": 9.0}])
        _push(agg, "w0", 2, gauges=[{"name": "depth", "labels": {}, "value": 2.0}])
        assert agg.stats()["resets_detected"] == 0
        assert agg.statusz()["fleet"]["gauges"]["depth"] == 2.0

    def test_version_skew_table(self):
        agg = MetricsAggregator("127.0.0.1", 0)
        bi = {"name": "build_info", "value": 1.0}
        _push(agg, "w0", 1, gauges=[{**bi, "labels": {"version": "0.6.0"}}])
        _push(agg, "w1", 1, gauges=[{**bi, "labels": {"version": "0.6.0"}}])
        assert not agg.statusz()["version_skew"]["skew"]
        _push(agg, "w2", 1, gauges=[{**bi, "labels": {"version": "0.5.0"}}])
        skew = agg.statusz()["version_skew"]
        assert skew["skew"] and len(skew["builds"]) == 2


# ---------------------------------------------------------------------------
# Wire contract.


class TestWire:
    def test_http_push_merge_and_409_skew(self):
        with MetricsAggregator("127.0.0.1", 0) as agg:
            body = json.dumps({
                "protocol": AGG_PROTOCOL, "instance": "w0", "role": "worker",
                "boot_id": "b", "seq": 1,
                "metrics": {"counters": [
                    {"name": "c", "labels": {}, "value": 2.0}]},
            }).encode()
            req = urllib.request.Request(
                agg.url + "/v1/push", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as resp:
                assert resp.status == 200

            stale = json.dumps({"protocol": AGG_PROTOCOL + 1, "instance": "x",
                                "seq": 1, "metrics": {}}).encode()
            req = urllib.request.Request(
                agg.url + "/v1/push", data=stale,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=5)
            assert ei.value.code == 409
            detail = json.loads(ei.value.read())
            assert detail["protocol"] == AGG_PROTOCOL

            with urllib.request.urlopen(agg.url + "/metrics", timeout=5) as r:
                _validate_prometheus(r.read().decode())

    def test_parse_aggregator_url(self):
        assert (parse_aggregator_url("http://127.0.0.1:9100/")
                == "http://127.0.0.1:9100")
        with pytest.raises(ValueError):
            parse_aggregator_url("ftp://x:1")
        with pytest.raises(ValueError):
            parse_aggregator_url("http://x:1/metrics")


# ---------------------------------------------------------------------------
# Pusher: fail-open degradation, refcounting.


class TestPusher:
    def test_exactly_one_degraded_event_per_transition(self):
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        reg = MetricsRegistry()
        reg.counter("c").inc()
        agg = MetricsAggregator("127.0.0.1", 0).start()
        try:
            pusher = TelemetryPusher(agg.url, role="worker", instance="w0",
                                     interval=60.0, cooldown=0.0, registry=reg)
            assert pusher.push_once()
        finally:
            agg.stop()
        # Aggregator gone: every retry fails but only the transition logs.
        for _ in range(4):
            reg.counter("c").inc()
            pusher.push_once()
        degraded = [r for r in sink.records
                    if r.get("name") == "aggregator_degraded"]
        assert len(degraded) == 1
        assert reg.counter("aggregator_degraded_total").value == 1.0

    def test_recovery_resends_full_snapshot(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(5)
        agg = MetricsAggregator("127.0.0.1", 0).start()
        url = agg.url
        try:
            pusher = TelemetryPusher(url, role="worker", instance="w0",
                                     interval=60.0, cooldown=0.0, registry=reg)
            assert pusher.push_once()
            agg.stop()
            assert not pusher.push_once()  # down → marks degraded
        finally:
            agg.stop()
        port = int(url.rsplit(":", 1)[1])
        with MetricsAggregator("127.0.0.1", port) as agg2:
            # Nothing changed since the last successful push, but the
            # post-failure push must resend the FULL snapshot or the new
            # (restarted) aggregator would never learn the counter.
            assert pusher.push_once()
            assert agg2.statusz()["fleet"]["counters"]["c"] == 5.0

    def test_acquire_pusher_refcounts_and_merges_roles(self):
        with MetricsAggregator("127.0.0.1", 0) as agg:
            p1 = acquire_pusher(agg.url, role="master", interval=60.0)
            p2 = acquire_pusher(agg.url, role="broker", interval=60.0)
            try:
                assert p1 is p2
                assert "master" in p1.role and "broker" in p1.role
            finally:
                release_pusher(p2, flush=False)
                release_pusher(p1, flush=False)

    def test_periodic_full_resend_keeps_rings_fresh(self):
        # Quiet series must keep receiving ring points (the heartbeat full
        # push) or a firing SLO over a flatlined series could never
        # observe the recovery and self-clear.
        reg = MetricsRegistry()
        reg.counter("c").inc()
        with MetricsAggregator("127.0.0.1", 0) as agg:
            pusher = TelemetryPusher(agg.url, role="worker", instance="w0",
                                     interval=60.0, full_every=3,
                                     registry=reg)
            for _ in range(7):
                assert pusher.push_once()
            ring = agg.ringz(name="c", instance="w0")["series"]
            # pushes 1 (first), 4 and 7 (heartbeats) land points even
            # though the counter never moved after the first push.
            assert len(ring) == 1 and len(ring[0]["points"]) == 3

    def test_build_info_present_after_start(self):
        reg = MetricsRegistry()
        with MetricsAggregator("127.0.0.1", 0) as agg:
            pusher = TelemetryPusher(agg.url, role="worker", instance="w0",
                                     interval=60.0, registry=reg)
            pusher.start()
            try:
                pusher.flush(timeout=5.0)
            finally:
                pusher.stop(flush=False)
            labels = build_info_labels()
            text = agg.render_prometheus()
            assert "build_info" in text
            assert f'version="{labels["version"]}"' in text


# ---------------------------------------------------------------------------
# SLO engine: fire, self-clear, flap damping.


def _mk_view(points_by_name):
    def view(pattern, **_):
        from gentun_tpu.telemetry.slo import match_series
        return [SeriesPoints(name, {"instance": "w0", "role": "worker"}, pts)
                for name, pts in points_by_name.items()
                if match_series(pattern, name)]
    return view


class TestSloEngine:
    RULE = SloRule(name="r", kind="increase", series="errors_total",
                   threshold=0.0, op=">", window_s=60.0, for_s=0.0,
                   clear_for_s=10.0, subject="fleet")

    def test_fire_then_self_clear(self):
        eng = SloEngine([self.RULE])
        t0 = 1000.0
        grow = [(t0 - 30, 0.0), (t0, 3.0)]
        fired = eng.evaluate(_mk_view({"errors_total": grow}), now=t0)
        assert [t["event"] for t in fired] == ["fire"]
        assert eng.active()

        # The window slides past the burst → healthy, but the clear hold
        # must elapse before the alert resolves.
        flat = [(t0 + 50, 3.0), (t0 + 65, 3.0)]
        assert eng.evaluate(_mk_view({"errors_total": flat}), now=t0 + 65) == []
        assert eng.active()  # clearing, not cleared
        cleared = eng.evaluate(
            _mk_view({"errors_total": flat + [(t0 + 80, 3.0)]}), now=t0 + 80)
        assert [t["event"] for t in cleared] == ["clear"]
        assert not eng.active()

    def test_flap_damping_no_duplicate_fire(self):
        eng = SloEngine([self.RULE])
        t0 = 1000.0
        grow = [(t0 - 30, 0.0), (t0, 1.0)]
        assert len(eng.evaluate(_mk_view({"errors_total": grow}), now=t0)) == 1
        # healthy for a moment (but < clear_for_s) ...
        flat = [(t0 + 1, 1.0), (t0 + 2, 1.0)]
        eng.evaluate(_mk_view({"errors_total": flat}), now=t0 + 2)
        # ... then breaching again: damped — NO second fire event.
        grow2 = flat + [(t0 + 3, 5.0)]
        assert eng.evaluate(_mk_view({"errors_total": grow2}), now=t0 + 3) == []
        assert len(eng.active()) == 1

    def test_for_s_hold_before_firing(self):
        rule = SloRule(name="r", kind="increase", series="errors_total",
                       threshold=0.0, op=">", window_s=60.0, for_s=5.0,
                       clear_for_s=1.0, subject="fleet")
        eng = SloEngine([rule])
        t0 = 1000.0
        grow = [(t0 - 30, 0.0), (t0, 1.0)]
        assert eng.evaluate(_mk_view({"errors_total": grow}), now=t0) == []
        grow.append((t0 + 6, 2.0))
        fired = eng.evaluate(_mk_view({"errors_total": grow}), now=t0 + 6)
        assert [t["event"] for t in fired] == ["fire"]

    def test_ratio_abstains_on_empty_denominator(self):
        rule = SloRule(name="hit_rate", kind="ratio", series="hits_total",
                       denom="misses_total", denom_includes_series=True,
                       threshold=0.05, op="<", window_s=60.0, for_s=0.0,
                       clear_for_s=1.0, subject="fleet")
        eng = SloEngine([rule])
        view = _mk_view({"hits_total": [(990.0, 0.0), (1000.0, 0.0)],
                         "misses_total": [(990.0, 0.0), (1000.0, 0.0)]})
        assert eng.evaluate(view, now=1000.0) == []
        assert not eng.active()

    def test_default_rules_scale_windows_not_thresholds(self):
        full = {r.name: r for r in default_rules()}
        scaled = {r.name: r for r in default_rules(scale=0.1)}
        assert full.keys() == scaled.keys()
        for name in full:
            assert scaled[name].threshold == full[name].threshold
            assert scaled[name].window_s < full[name].window_s

    def test_aggregator_end_to_end_alert(self):
        rule = SloRule(name="deg", kind="increase", series="*_degraded_total",
                       threshold=0.0, op=">", window_s=60.0, for_s=0.0,
                       clear_for_s=3600.0, subject="instance")
        agg = MetricsAggregator("127.0.0.1", 0, slo_rules=[rule])
        _push(agg, "w0", 1, counters=[
            {"name": "fitness_service_degraded_total", "labels": {}, "value": 0.0}])
        time.sleep(0.05)
        _push(agg, "w0", 2, counters=[
            {"name": "fitness_service_degraded_total", "labels": {}, "value": 1.0}])
        fired = agg.evaluate_slos()
        assert [t["event"] for t in fired] == ["fire"]
        snap = agg.alertz()
        assert snap["active"] and snap["active"][0]["rule"] == "deg"
        assert snap["active"][0]["subject"] == "w0"


# ---------------------------------------------------------------------------
# /ringz: the dashboard's raw-ring query endpoint.


class TestRingz:
    def _seed(self, agg):
        _push(agg, "cn0", 1, role="canary", counters=[
            {"name": "canary_probes_total", "labels": {"result": "ok"},
             "value": 3.0}])
        _push(agg, "w0", 1, counters=[
            {"name": "jobs_dispatched_total", "labels": {}, "value": 10.0}],
            histograms=[{"name": "canary_e2e_seconds", "labels": {},
                         "count": 3, "sum": 0.9,
                         "buckets": [[1.0, 3.0], ["+Inf", 3.0]]}])

    def _get(self, agg, query):
        with urllib.request.urlopen(agg.url + "/ringz" + query,
                                    timeout=5) as r:
            assert r.status == 200
            return json.loads(r.read())

    def test_name_filter_exact_and_wildcard(self):
        with MetricsAggregator("127.0.0.1", 0) as agg:
            self._seed(agg)
            exact = self._get(agg, "?name=canary_probes_total")
            assert [s["name"] for s in exact["series"]] == \
                ["canary_probes_total"]
            assert exact["series"][0]["labels"]["result"] == "ok"
            assert exact["series"][0]["labels"]["instance"] == "cn0"
            assert exact["series"][0]["points"][-1][1] == 3.0
            assert exact["ring_len"] == agg.ring_len

            wild = self._get(agg, "?name=canary_*")
            names = sorted(s["name"] for s in wild["series"])
            # Histograms surface as _sum/_count series — the exact shape
            # the canary_latency ratio rule consumes.
            assert names == ["canary_e2e_seconds_count",
                             "canary_e2e_seconds_sum",
                             "canary_probes_total"]

            everything = self._get(agg, "")  # default name=*
            assert {s["name"] for s in everything["series"]} >= set(names) | \
                {"jobs_dispatched_total"}

    def test_instance_filter(self):
        with MetricsAggregator("127.0.0.1", 0) as agg:
            self._seed(agg)
            only = self._get(agg, "?name=*&instance=cn0")
            assert {s["labels"]["instance"] for s in only["series"]} == {"cn0"}
            assert {s["name"] for s in only["series"]} == \
                {"canary_probes_total"}
            # Unknown instance: empty, not an error.
            assert self._get(agg, "?instance=ghost")["series"] == []

    def test_unknown_series_is_empty_not_error(self):
        with MetricsAggregator("127.0.0.1", 0) as agg:
            self._seed(agg)
            assert self._get(agg, "?name=no_such_metric")["series"] == []

    def test_canary_series_retained_through_counter_reset_fold(self):
        # A canary daemon restart must not dent the drift/probe history
        # the correctness rule judges: the ring keeps reset-CORRECTED
        # values, so window deltas stay plain subtraction across a
        # restart fold.
        agg = MetricsAggregator("127.0.0.1", 0)
        _push(agg, "cn0", 1, boot="boot-a", role="canary", counters=[
            {"name": "canary_fitness_drift_total", "labels": {},
             "value": 2.0}])
        time.sleep(0.01)
        _push(agg, "cn0", 1, boot="boot-b", role="canary", counters=[
            {"name": "canary_fitness_drift_total", "labels": {},
             "value": 1.0}])  # restarted daemon: cumulative went DOWN
        rz = agg.ringz(name="canary_fitness_drift_total")
        [series] = rz["series"]
        values = [v for _t, v in series["points"]]
        # 2 pre-restart drifts folded into base, +1 after: monotone 2→3,
        # never the raw 1.0 a naive ring would show.
        assert values[0] == 2.0 and values[-1] == 3.0
        assert 1.0 not in values
        assert values == sorted(values)
