"""Black-box canary plane (telemetry/canary.py + the fitness_corrupt
fault + broker session tagging / TTFD plumbing).

The canary is the fleet's synthetic monitor: golden-genome probe
sessions through the REAL serving path, decomposed into golden-signal
SLIs, with a zero-tolerance bit-equality check on every returned
fitness.  These tests pin the pieces separately — golden sealing, the
fault kind, the no_memo dedup bypass, tenant invisibility of tagged
sessions, the TTFD stamps — and then the whole loop end to end against
a live broker + worker, including drift detection and the error SLIs.
"""

import contextlib
import json
import struct
import threading
import time
import urllib.request

import numpy as np
import pytest

from gentun_tpu import Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import GentunClient, JobBroker, SessionClient
from gentun_tpu.distributed.faults import FaultInjector, FaultPlan, FaultSpec
from gentun_tpu.distributed.sessions import SessionRegistry
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.canary import CANARY_TAG, CanaryDaemon, GoldenSet
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.telemetry.slo import default_rules


class OneMax(Individual):
    evaluations = 0  # class-level: counts REAL evaluations across jobs

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        type(self).evaluations += 1
        return float(sum(sum(g) for g in self.genes.values()))


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _spawn_worker(species, port, worker_id, fault_injector=None, **kw):
    stop = threading.Event()
    client = GentunClient(
        species, *DATA, host="127.0.0.1", port=port, capacity=1,
        worker_id=worker_id, heartbeat_interval=0.2, reconnect_delay=0.05,
        fault_injector=fault_injector, **kw)
    t = threading.Thread(target=lambda: client.work(stop_event=stop),
                         daemon=True)
    t.start()
    return client, stop, t


def _probes(n=2, seed=0):
    pop = Population(OneMax, DATA, size=n, seed=seed, maximize=True)
    return [{"genes": ind.get_genes()} for ind in pop]


@contextlib.contextmanager
def _broker(**kw):
    b = JobBroker(port=0, **kw).start()
    try:
        yield b
    finally:
        b.stop()


def _counter_total(name, **labels):
    snap = get_registry().snapshot()
    total = 0.0
    for c in snap["counters"]:
        if c["name"] != name:
            continue
        if labels and any((c.get("labels") or {}).get(k) != v
                          for k, v in labels.items()):
            continue
        total += c["value"]
    return total


# ---------------------------------------------------------------------------
# GoldenSet: content-addressed, sealed at first evaluation
# ---------------------------------------------------------------------------


class TestGoldenSet:
    def test_first_seal_wins(self):
        g = GoldenSet()
        key = GoldenSet.key("space", "fp", "gk")
        sealed, newly = g.seal(key, 3.5)
        assert (sealed, newly) == (3.5, True)
        # A later (possibly corrupt) value never overwrites the truth.
        sealed, newly = g.seal(key, 99.0)
        assert (sealed, newly) == (3.5, False)
        assert g.get(key) == 3.5 and len(g) == 1

    def test_key_is_the_identity_triple(self):
        assert GoldenSet.key("s", "f", "g") == "s:f:g"
        assert GoldenSet.key("s2", "f", "g") != GoldenSet.key("s", "f", "g")

    def test_persistence_roundtrip(self, tmp_path):
        path = str(tmp_path / "golden.json")
        g = GoldenSet(path)
        g.seal("a:b:c", 1.25)
        g.seal("a:b:d", -0.0)
        g2 = GoldenSet(path)
        assert g2.get("a:b:c") == 1.25
        # Bit-level survival: -0.0 must come back as -0.0, not 0.0.
        assert struct.pack("<d", g2.get("a:b:d")) == struct.pack("<d", -0.0)

    def test_unreadable_file_starts_empty(self, tmp_path):
        path = tmp_path / "golden.json"
        path.write_text("{not json")
        g = GoldenSet(str(path))
        assert len(g) == 0


# ---------------------------------------------------------------------------
# fitness_corrupt fault kind (faults.py)
# ---------------------------------------------------------------------------


class TestFitnessCorruptFault:
    def test_spec_valid_only_at_worker_pre_eval(self):
        FaultSpec(hook="worker_pre_eval", kind="fitness_corrupt")  # ok
        with pytest.raises(ValueError):
            FaultSpec(hook="broker_send", kind="fitness_corrupt")

    def test_mark_is_consumed_once(self):
        inj = FaultInjector(FaultPlan([FaultSpec(
            hook="worker_pre_eval", kind="fitness_corrupt", at=0)]))
        inj.worker_pre_eval(None, {"job_id": "j1"})
        assert inj.take_fitness_corrupt("j1") is True
        assert inj.take_fitness_corrupt("j1") is False  # consumed
        assert inj.take_fitness_corrupt("j2") is False  # never marked
        assert inj.fired and inj.fired[0]["kind"] == "fitness_corrupt"

    def test_corrupt_fitness_is_deterministic_and_finite(self):
        assert FaultInjector.corrupt_fitness(6.0) == 7.0
        assert FaultInjector.corrupt_fitness(6.0) == 7.0  # same in, same out
        assert FaultInjector.corrupt_fitness(float("nan")) == 1.0
        assert FaultInjector.corrupt_fitness(float("inf")) == 1.0
        assert FaultInjector.corrupt_fitness("junk") == 1.0
        # Never bit-equal to the input.
        for v in (0.0, -1.5, 1e300):
            assert struct.pack("<d", FaultInjector.corrupt_fitness(v)) != \
                struct.pack("<d", v)


# ---------------------------------------------------------------------------
# Session tag + TTFD plumbing (sessions.py / broker.py)
# ---------------------------------------------------------------------------


class TestSessionTag:
    def test_registry_tag_roundtrip_and_snapshot(self):
        reg = SessionRegistry()
        sess = reg.open("probe", tag=CANARY_TAG)
        assert sess.tag == CANARY_TAG
        assert reg.open("tenant").tag is None
        snap = sess.snapshot()
        assert snap["tag"] == CANARY_TAG
        # Untagged snapshots keep the pre-tag schema (no new key).
        assert "tag" not in reg.open("tenant").snapshot()

    def test_reopen_updates_tag(self):
        reg = SessionRegistry()
        reg.open("s1")
        assert reg.open("s1", tag=CANARY_TAG).tag == CANARY_TAG

    def test_canary_sessions_excluded_from_flow_gauges(self):
        spans_mod.enable()
        with _broker() as broker:
            port = broker.address[1]
            broker.open_session("tenant-a")
            broker.open_session("probe-1", weight=1e-6, max_in_flight=1,
                                tag=CANARY_TAG)
            _, stop, _ = _spawn_worker(OneMax, port, "tg-w0")
            try:
                genes = _probes(1)[0]["genes"]
                broker.submit({"t-j0": {"genes": genes}}, session="tenant-a")
                broker.submit({"p-j0": {"genes": genes}}, session="probe-1")
                broker.gather(["t-j0", "p-j0"], timeout=30)
                snap = get_registry().snapshot()
                tagged = {(g["name"], (g.get("labels") or {}).get("session"))
                          for g in snap["gauges"]
                          if "session" in (g.get("labels") or {})}
                assert ("session_in_flight", "tenant-a") in tagged
                assert not any(s == "probe-1" for _, s in tagged), tagged
                # Nor any canary-labeled queue_wait_s series.
                qw = [(h.get("labels") or {}).get("session")
                      for h in snap["histograms"]
                      if h["name"] == "queue_wait_s"]
                assert "probe-1" not in qw
            finally:
                stop.set()

    def test_ttfd_stamped_and_cleared_on_close(self):
        with _broker() as broker:
            port = broker.address[1]
            broker.open_session("s-ttfd")
            assert broker.session_ttfd("s-ttfd") is None  # nothing submitted
            _, stop, _ = _spawn_worker(OneMax, port, "tt-w0")
            try:
                genes = _probes(1)[0]["genes"]
                broker.submit({"j0": {"genes": genes}}, session="s-ttfd")
                broker.gather(["j0"], timeout=30)
                ttfd = broker.session_ttfd("s-ttfd")
                assert ttfd is not None and ttfd >= 0.0
                broker.close_session("s-ttfd")
                deadline = time.monotonic() + 5
                while (broker.session_ttfd("s-ttfd") is not None
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                assert broker.session_ttfd("s-ttfd") is None
            finally:
                stop.set()

    def test_wire_session_stats_carries_ttfd(self):
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "ws-w0")
            client = SessionClient("127.0.0.1", port)
            try:
                sid = client.open_session("s-wire", tag=CANARY_TAG)
                stats = client.session_stats(sid)
                assert "ttfd_s" not in stats  # pre-dispatch: old byte layout
                genes = _probes(1)[0]["genes"]
                [jid] = client.submit(sid, {"wj0": {"genes": genes}})
                r, f = client.wait_any([jid], timeout=30)
                assert r and not f
                stats = client.session_stats(sid)
                assert stats["ttfd_s"] >= 0.0
            finally:
                client.close()
                stop.set()


# ---------------------------------------------------------------------------
# no_memo: the canary's fitness-cache dedup bypass (client.py)
# ---------------------------------------------------------------------------


class TestNoMemo:
    def test_no_memo_jobs_always_really_evaluate(self):
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "nm-w0")
            try:
                genes = _probes(1)[0]["genes"]
                OneMax.evaluations = 0
                # Two no_memo submits of the SAME genome: the worker's
                # per-group cache must not dedup the second into a hit.
                broker.submit({"n-j0": {"genes": genes, "no_memo": True}})
                broker.gather(["n-j0"], timeout=30)
                broker.submit({"n-j1": {"genes": genes, "no_memo": True}})
                broker.gather(["n-j1"], timeout=30)
                assert OneMax.evaluations == 2
            finally:
                stop.set()

    def test_memoizing_jobs_unaffected(self):
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "nm-w1")
            try:
                genes = _probes(1, seed=3)[0]["genes"]
                res = broker.evaluate({"m-j0": {"genes": genes}}, timeout=30)
                assert res["m-j0"] == float(
                    sum(sum(g) for g in genes.values()))
            finally:
                stop.set()


# ---------------------------------------------------------------------------
# Stock canary SLO rules (telemetry/slo.py)
# ---------------------------------------------------------------------------


class TestCanaryRules:
    def test_default_rules_include_the_canary_triple(self):
        rules = {r.name: r for r in default_rules()}
        assert rules["canary_error_burn"].series == "canary_errors_total"
        assert rules["canary_error_burn"].severity == "warn"
        latency = rules["canary_latency"]
        assert latency.kind == "ratio"
        assert latency.series == "canary_e2e_seconds_sum"
        assert latency.denom == "canary_e2e_seconds_count"
        correctness = rules["canary_correctness"]
        assert correctness.series == "canary_fitness_drift_total"
        assert correctness.severity == "page"
        assert correctness.threshold == 0.0 and correctness.op == ">"
        # Zero tolerance: no for_s hold — the first drift pages.
        assert correctness.for_s == 0.0

    def test_scale_shrinks_windows_not_thresholds(self):
        full = {r.name: r for r in default_rules()}
        drill = {r.name: r for r in default_rules(0.1)}
        for name in ("canary_error_burn", "canary_latency",
                     "canary_correctness"):
            assert drill[name].window_s == pytest.approx(
                full[name].window_s * 0.1)
            assert drill[name].threshold == full[name].threshold


# ---------------------------------------------------------------------------
# CanaryDaemon end to end
# ---------------------------------------------------------------------------


class TestCanaryDaemon:
    def test_probe_cycle_seals_then_verifies(self):
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "cd-w0")
            cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                              space_key="onemax", probe_interval=999,
                              probe_timeout=15, serve_http=False)
            try:
                r1 = cn.probe_once()
                assert r1["result"] == "ok" and r1["newly_sealed"]
                assert r1["open_s"] >= 0 and r1["e2e_s"] >= r1["open_s"]
                assert r1["ttfd_s"] >= 0.0
                r2 = cn.probe_once()
                assert r2["result"] == "ok" and not r2["newly_sealed"]
                assert r2["sealed"] == r1["fitness"]
                assert _counter_total("canary_probes_total", result="ok") == 2
                assert _counter_total("canary_fitness_drift_total") == 0
            finally:
                cn.stop()
                stop.set()

    def test_drift_detected_within_one_cycle(self):
        inj = FaultInjector(FaultPlan([FaultSpec(
            hook="worker_pre_eval", kind="fitness_corrupt", at=1)]))
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "cd-w1",
                                       fault_injector=inj)
            cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                              space_key="onemax", probe_interval=999,
                              probe_timeout=15, serve_http=False)
            try:
                assert cn.probe_once()["result"] == "ok"  # seals the truth
                r = cn.probe_once()  # the corrupted cycle
                assert r["result"] == "drift"
                assert r["fitness"] != r["sealed"]
                assert _counter_total("canary_fitness_drift_total") == 1
            finally:
                cn.stop()
                stop.set()

    def test_workerless_fleet_probes_error_not_hang(self):
        with _broker() as broker:
            port = broker.address[1]
            cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                              probe_interval=999, probe_timeout=0.5,
                              serve_http=False)
            try:
                r = cn.probe_once()
                assert r["result"] == "error" and r["stage"] == "result"
                assert _counter_total("canary_errors_total",
                                      stage="result") == 1
            finally:
                cn.stop()

    def test_dead_broker_probes_error_at_open(self):
        # Grab a port nobody listens on.
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                          probe_interval=999, probe_timeout=0.5,
                          serve_http=False)
        try:
            r = cn.probe_once()
            assert r["result"] == "error" and r["stage"] == "open"
            assert _counter_total("canary_errors_total", stage="open") == 1
        finally:
            cn.stop()

    def test_http_plane(self):
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "cd-w2")
            cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                              probe_interval=999, probe_timeout=15,
                              serve_http=True)
            cn.start()
            try:
                cn.probe_once()
                hz = json.loads(urllib.request.urlopen(
                    cn.url + "/healthz").read())
                assert hz["status"] == "ok" and hz["cycles"] == 1
                sz = json.loads(urllib.request.urlopen(
                    cn.url + "/statusz").read())
                assert sz["config"]["probes"] == 1
                assert len(sz["goldens"]) == 1
                cz = json.loads(urllib.request.urlopen(
                    cn.url + "/canaryz").read())
                assert cz["total"] == 1 and cz["ok"] == 1
                assert cz["probes"][0]["result"] == "ok"
                with pytest.raises(urllib.error.HTTPError):
                    urllib.request.urlopen(cn.url + "/nope")
            finally:
                cn.stop()
                stop.set()

    def test_golden_persists_across_daemon_restarts(self, tmp_path):
        path = str(tmp_path / "golden.json")
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "cd-w3")
            try:
                cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                                  space_key="onemax", probe_interval=999,
                                  probe_timeout=15, golden_path=path,
                                  serve_http=False)
                r1 = cn.probe_once()
                assert r1["newly_sealed"]
                cn.stop()
                # A NEW daemon must verify against the persisted seal,
                # not re-seal.
                cn2 = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                                   space_key="onemax", probe_interval=999,
                                   probe_timeout=15, golden_path=path,
                                   serve_http=False)
                r2 = cn2.probe_once()
                assert not r2["newly_sealed"] and r2["result"] == "ok"
                cn2.stop()
            finally:
                stop.set()

    def test_telemetry_records_probe_and_drift(self):
        sink_records = []

        class _Sink:
            def record(self, rec):
                sink_records.append(rec)

        spans_mod.enable()
        spans_mod.set_run_sink(_Sink())
        inj = FaultInjector(FaultPlan([FaultSpec(
            hook="worker_pre_eval", kind="fitness_corrupt", at=1)]))
        with _broker() as broker:
            port = broker.address[1]
            _, stop, _ = _spawn_worker(OneMax, port, "cd-w4",
                                       fault_injector=inj)
            cn = CanaryDaemon([f"127.0.0.1:{port}"], _probes(1),
                              probe_interval=999, probe_timeout=15,
                              serve_http=False)
            try:
                cn.probe_once()
                cn.probe_once()
                probes = [r for r in sink_records
                          if r.get("type") == "canary_probe"]
                assert len(probes) == 2
                assert probes[1]["result"] == "drift"
                drifts = [r for r in sink_records
                          if r.get("type") == "event"
                          and r.get("name") == "canary_drift"]
                assert len(drifts) == 1
            finally:
                cn.stop()
                stop.set()

    def test_needs_probes_and_brokers(self):
        with pytest.raises(ValueError):
            CanaryDaemon(["127.0.0.1:1"], [], serve_http=False)
        with pytest.raises(ValueError):
            CanaryDaemon([], _probes(1), serve_http=False)
