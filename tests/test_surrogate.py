"""Surrogate rung −1 (``surrogate.py``): the ledger-trained fitness
ranker that gates dispatch under the ASHA ladder.

Covers the PR's acceptance gates: deterministic encoding and ridge
model, quantile-gate admission semantics (admit-all until trained,
reject-streak force-admit), fail-open degradation with exactly ONE
event per transition, warm-start from the dataset plane, checkpoint
schema v4 round-trips carrying surrogate state + PENDING gate
decisions, v3 forward-compat in both directions, and the engine-level
off-path bit-identity contract (PR 2: one attribute read when off).
"""

import json

import numpy as np
import pytest

from gentun_tpu import AsyncEvolution, Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import FaultInjector, FaultPlan, FaultSpec
from gentun_tpu.distributed.faults import MasterKilled
from gentun_tpu.surrogate import (
    FitnessSurrogate,
    SurrogateGate,
    encode_genes,
    space_key,
)
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.utils import CHECKPOINT_SCHEMA, Checkpointer


class OneMax(Individual):
    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _pop(size=8, seed=11, **kw):
    return Population(OneMax, DATA, size=size, seed=seed, maximize=True, **kw)


def _genes(bits):
    return {"S_1": tuple(bits[:6]), "S_2": tuple(bits[6:])}


def _rand_genes(rng):
    return _genes([int(b) for b in rng.integers(0, 2, 12)])


def _trained_surrogate(n=40, seed=0, **kw):
    rng = np.random.default_rng(seed)
    kw.setdefault("min_train", 16)
    kw.setdefault("refit_every", 16)
    sur = FitnessSurrogate(**kw)
    for _ in range(n):
        g = _rand_genes(rng)
        sur.observe(g, 0, float(sum(sum(v) for v in g.values())))
    return sur


class _FakeDatasetClient:
    """In-memory stand-in for FitnessServiceClient's dataset plane."""

    def __init__(self, rows=None, fail=False):
        self.rows = list(rows or [])
        self.fail = fail
        self.published = []

    def publish_dataset(self, space, rows):
        if self.fail:
            return None
        self.published.append((space, list(rows)))
        self.rows.extend(rows)
        return len(rows)

    def fetch_dataset(self, space, limit=4096):
        if self.fail:
            return None
        return list(self.rows)[-limit:]


class TestEncoding:
    def test_bias_sorted_bits_and_rung(self):
        g = {"S_2": (1, 0), "S_1": (0, 1, 1)}
        assert encode_genes(g, rung=2) == [1.0, 0.0, 1.0, 1.0, 1.0, 0.0, 2.0]

    def test_scalar_and_exotic_values_are_total(self):
        g = {"a": 3, "b": "relu"}
        x = encode_genes(g)
        assert x[0] == 1.0 and x[1] == 3.0 and 0.0 <= x[2] < 1.0
        assert x == encode_genes(g)  # hashed column is deterministic

    def test_fixed_width_across_genomes(self):
        rng = np.random.default_rng(3)
        widths = {len(encode_genes(_rand_genes(rng))) for _ in range(20)}
        assert widths == {14}  # bias + 12 bits + rung

    def test_space_key_namespaced_and_width_sensitive(self):
        g = _genes([0] * 12)
        assert space_key(g).startswith("default:")
        assert space_key(g, "tenant-a") != space_key(g)
        assert space_key(g, "tenant-a") == space_key(_genes([1] * 12), "tenant-a")
        wider = {"S_1": (0,) * 6, "S_2": (0,) * 8}
        assert space_key(wider) != space_key(g)


class TestFitnessSurrogate:
    def test_min_train_gate(self):
        sur = FitnessSurrogate(min_train=4, refit_every=2)
        rng = np.random.default_rng(0)
        for i in range(3):
            assert not sur.observe(_rand_genes(rng), 0, float(i))
            assert sur.score(_rand_genes(rng)) is None
        assert sur.observe(_rand_genes(rng), 0, 3.0)  # 4th row fires the fit
        assert sur.trained

    def test_ctor_validation(self):
        with pytest.raises(ValueError, match="min_train"):
            FitnessSurrogate(min_train=1)
        with pytest.raises(ValueError, match="refit_every"):
            FitnessSurrogate(refit_every=0)

    def test_refit_cadence(self):
        sur = FitnessSurrogate(min_train=4, refit_every=4)
        rng = np.random.default_rng(1)
        fired = [sur.observe(_rand_genes(rng), 0, float(i)) for i in range(12)]
        assert fired == [False] * 3 + [True] + [False] * 3 + [True] + [False] * 3 + [True]
        assert sur.refits == 3

    def test_learns_onemax_ranking(self):
        sur = _trained_surrogate()
        lo = sur.score(_genes([0] * 12))
        hi = sur.score(_genes([1] * 12))
        assert lo is not None and hi is not None and hi > lo

    def test_deterministic_given_stream(self):
        a, b = _trained_surrogate(seed=7), _trained_surrogate(seed=7)
        assert a._weights == b._weights
        g = _genes([1, 0] * 6)
        assert a.score(g) == b.score(g)

    def test_width_mismatch_scores_none(self):
        sur = _trained_surrogate()
        assert sur.score({"S_1": (1, 0)}) is None

    def test_max_samples_evicts_oldest(self):
        sur = FitnessSurrogate(min_train=2, max_samples=8)
        rng = np.random.default_rng(2)
        for i in range(20):
            sur.add_row(f"g{i}", encode_genes(_rand_genes(rng)), float(i))
        assert sur.n_samples == 8
        assert ("g0", 0) not in sur._samples and ("g19", 0) in sur._samples

    def test_state_round_trip(self):
        sur = _trained_surrogate()
        clone = FitnessSurrogate()
        clone.load_state_dict(json.loads(json.dumps(sur.state_dict())))
        g = _genes([1, 1, 0] * 4)
        assert clone.score(g) == sur.score(g)
        assert clone.n_samples == sur.n_samples
        assert clone.refits == sur.refits


class TestSurrogateGate:
    def _gate(self, **kw):
        kw.setdefault("surrogate", _trained_surrogate())
        kw.setdefault("eta", 4)
        kw.setdefault("window", 16)
        kw.setdefault("min_window", 8)
        gate = SurrogateGate(**kw)
        gate.prepare(_genes([0] * 12), maximize=True)
        return gate

    def test_eta_validation(self):
        with pytest.raises(ValueError, match="eta"):
            SurrogateGate(eta=1)

    def test_admit_all_until_trained(self):
        gate = self._gate(surrogate=FitnessSurrogate(min_train=32))
        rng = np.random.default_rng(4)
        decisions = [gate.decide(_rand_genes(rng)) for _ in range(10)]
        assert all(admit for admit, _ in decisions)
        assert all(score is None for _, score in decisions)

    def test_quantile_cut_rejects_poor_children(self):
        gate = self._gate()
        for bits in range(8, 12):  # fill the window with strong scores
            for _ in range(4):
                gate.decide(_genes([1] * bits + [0] * (12 - bits)))
        admit, score = gate.decide(_genes([0] * 12))
        assert not admit and score is not None
        admit, _ = gate.decide(_genes([1] * 12))
        assert admit

    def test_reject_streak_force_admits(self):
        gate = self._gate(max_reject_streak=3)
        for bits in range(8, 12):
            for _ in range(4):
                gate.decide(_genes([1] * bits + [0] * (12 - bits)))
        bad = _genes([0] * 12)
        outcomes = [gate.decide(bad)[0] for _ in range(6)]
        assert outcomes[:2] == [False, False]
        assert True in outcomes[2:]  # the cap let one through

    def test_decide_is_deterministic(self):
        a, b = self._gate(), self._gate()
        rng_a, rng_b = np.random.default_rng(5), np.random.default_rng(5)
        for _ in range(40):
            assert a.decide(_rand_genes(rng_a)) == b.decide(_rand_genes(rng_b))
        assert (a.admitted, a.rejected) == (b.admitted, b.rejected)

    def test_pending_resolves_into_precision(self):
        gate = self._gate()
        rng = np.random.default_rng(6)
        admitted = []
        for _ in range(40):
            g = _rand_genes(rng)
            admit, _ = gate.decide(g)
            if admit:
                admitted.append(g)
        for g in admitted:
            gate.observe_result(g, 0, float(sum(sum(v) for v in g.values())))
        assert not gate._pending
        assert gate.precision_at_k is not None
        assert 0.0 <= gate.precision_at_k <= 1.0

    def test_forget_drops_pending(self):
        gate = self._gate()
        g = _genes([1] * 12)
        gate.decide(g)
        assert gate._pending
        gate.forget(g)
        assert not gate._pending

    def test_counters_and_sampled_histogram(self):
        spans_mod.enable()
        gate = self._gate()
        rng = np.random.default_rng(7)
        n = 64
        for _ in range(n):
            gate.decide(_rand_genes(rng))
        reg = get_registry()
        total = (reg.counter("surrogate_gate_admitted_total").value
                 + reg.counter("surrogate_gate_rejected_total").value)
        assert total == n == gate.admitted + gate.rejected
        hist = reg.histogram("surrogate_score_seconds")
        # Latency is sampled 1-in-(mask+1), not per decide.
        assert hist.count == n // (SurrogateGate._SAMPLE_MASK + 1)

    def test_state_round_trip_with_pending(self):
        gate = self._gate()
        rng = np.random.default_rng(8)
        for _ in range(20):
            gate.decide(_rand_genes(rng))
        assert gate._pending
        state = json.loads(json.dumps(gate.state_dict()))
        clone = SurrogateGate.from_state(state)
        assert clone._pending == gate._pending
        assert clone._sorted == gate._sorted
        assert (clone.admitted, clone.rejected) == (gate.admitted, gate.rejected)
        g = _rand_genes(np.random.default_rng(9))
        assert clone.decide(g) == gate.decide(g)


class TestDatasetPlane:
    def test_warm_start_trains_from_service_rows(self):
        rng = np.random.default_rng(10)
        rows = []
        for i in range(20):
            g = _rand_genes(rng)
            rows.append({"genome": f"g{i}",
                         "genes": {k: list(v) for k, v in g.items()},
                         "rung": 0,
                         "fitness": float(sum(sum(v) for v in g.values()))})
        gate = SurrogateGate(FitnessSurrogate(min_train=16),
                             dataset_client=_FakeDatasetClient(rows=rows))
        gate.prepare(_genes([0] * 12), maximize=True)
        assert gate.surrogate.trained
        assert not gate.degraded

    def test_refit_boundary_publishes_rows(self):
        client = _FakeDatasetClient()
        gate = SurrogateGate(FitnessSurrogate(min_train=4, refit_every=4),
                             dataset_client=client)
        gate.prepare(_genes([0] * 12), maximize=True)
        rng = np.random.default_rng(11)
        for _ in range(8):
            g = _rand_genes(rng)
            gate.observe_result(g, 0, float(sum(sum(v) for v in g.values())))
        assert client.published  # synced at the refit boundary
        assert not gate._publish_buf

    def test_degradation_is_one_event_and_fail_open(self):
        class _ListSink:
            def __init__(self):
                self.records = []

            def record(self, rec):
                self.records.append(rec)

        spans_mod.enable()
        sink = _ListSink()
        spans_mod.set_run_sink(sink)
        try:
            client = _FakeDatasetClient(fail=True)
            gate = SurrogateGate(_trained_surrogate(min_train=4, refit_every=4),
                                 eta=4, window=16, min_window=8,
                                 dataset_client=client)
            gate.prepare(_genes([0] * 12), maximize=True)
            assert gate.degraded  # warm-start fetch already failed
            rng = np.random.default_rng(12)
            for _ in range(12):  # several refit boundaries, all failing
                g = _rand_genes(rng)
                gate.observe_result(g, 0, 1.0)
            assert gate.degraded_total == 1
            # Degraded ⇒ admit-all, even for children the cut would veto.
            for bits in range(8, 12):
                gate.decide(_genes([1] * bits + [0] * (12 - bits)))
            assert gate.decide(_genes([0] * 12))[0]
            events = [r for r in sink.records if r.get("type") == "event"
                      and r.get("name") == "surrogate_degraded"]
            assert len(events) == 1
        finally:
            spans_mod.set_run_sink(None)

    def test_recovery_on_successful_sync(self):
        client = _FakeDatasetClient(fail=True)
        gate = SurrogateGate(FitnessSurrogate(min_train=4, refit_every=4),
                             dataset_client=client)
        gate.prepare(_genes([0] * 12), maximize=True)
        assert gate.degraded
        client.fail = False
        rng = np.random.default_rng(13)
        for _ in range(8):
            g = _rand_genes(rng)
            gate.observe_result(g, 0, float(sum(sum(v) for v in g.values())))
        assert not gate.degraded
        assert gate.degraded_total == 1


def _gated(seed=11, **gate_kw):
    gate_kw.setdefault("surrogate", FitnessSurrogate(min_train=8, refit_every=8))
    gate_kw.setdefault("eta", 4)
    gate_kw.setdefault("window", 32)
    gate_kw.setdefault("min_window", 8)
    gate = SurrogateGate(**gate_kw)
    eng = AsyncEvolution(_pop(seed=seed), max_in_flight=1, seed=seed,
                         surrogate=gate, checkpoint_every=2)
    return eng, gate


def _sig(eng):
    return [(h["fitness"], h.get("rung")) for h in eng.history]


class TestEngineIntegration:
    def test_off_path_unchanged(self, tmp_path):
        """surrogate=None: deterministic, and the checkpoint carries no
        surrogate key at all (the off-path wire/disk format is
        byte-compatible with an engine that predates the gate)."""
        path = str(tmp_path / "ck.json")
        a = AsyncEvolution(_pop(), max_in_flight=1, seed=5, checkpoint_every=4)
        a.run(max_evaluations=20, checkpointer=Checkpointer(path))
        b = AsyncEvolution(_pop(), max_in_flight=1, seed=5)
        b.run(max_evaluations=20)
        assert _sig(a) == _sig(b)
        state = json.load(open(path))
        assert "surrogate" not in state
        assert "surrogate" not in a._ops_status()

    def test_gated_run_deterministic_and_rejects_rebreed(self):
        ea, ga = _gated()
        ea.run(max_evaluations=40)
        eb, gb = _gated()
        eb.run(max_evaluations=40)
        assert _sig(ea) == _sig(eb)
        assert (ga.admitted, ga.rejected) == (gb.admitted, gb.rejected)
        assert ga.rejected > 0  # the gate actually vetoed children
        assert ea.completed == 40  # rejections never consumed budget

    def test_checkpoint_v4_carries_surrogate_and_pending(self, tmp_path):
        path = str(tmp_path / "ck.json")
        eng, gate = _gated()
        eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        assert state["schema_version"] == CHECKPOINT_SCHEMA == 4
        sur = state["surrogate"]
        assert sur["model"]["weights"] is not None
        assert sur["scores"]
        assert isinstance(sur["pending"], list)

    def test_kill_resume_bit_identical(self, tmp_path):
        ref, _ = _gated()
        ref.run(max_evaluations=40)
        resumed_ok = False
        for at in range(2, 16):
            path = str(tmp_path / f"ck-{at}.json")
            eng, _ = _gated()
            eng.set_fault_injector(FaultInjector(FaultPlan([
                FaultSpec(hook="master_boundary", kind="kill_master", at=at)])))
            with pytest.raises(MasterKilled):
                eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
            state = json.load(open(path))
            if not (state.get("surrogate") or {}).get("pending"):
                continue
            eng2, _ = _gated()
            eng2.run(max_evaluations=40, checkpointer=Checkpointer(path))
            assert _sig(eng2) == _sig(ref)
            resumed_ok = True
            break
        assert resumed_ok, "no kill boundary carried pending gate decisions"

    def test_resume_reconstructs_gate_without_ctor_surrogate(self, tmp_path):
        """The checkpoint wins (ladder precedent): resuming WITHOUT a
        ctor surrogate rebuilds the gate from checkpoint state."""
        path = str(tmp_path / "ck.json")
        eng, gate = _gated()
        eng.set_fault_injector(FaultInjector(FaultPlan([
            FaultSpec(hook="master_boundary", kind="kill_master", at=4)])))
        with pytest.raises(MasterKilled):
            eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        ref, _ = _gated()
        ref.run(max_evaluations=40)
        eng2 = AsyncEvolution(_pop(), max_in_flight=1, seed=11,
                              checkpoint_every=2)
        eng2.run(max_evaluations=40, checkpointer=Checkpointer(path))
        assert eng2._surrogate is not None
        assert _sig(eng2) == _sig(ref)

    def test_v3_checkpoint_still_loads(self, tmp_path):
        """Forward compat: a pre-surrogate (v3) checkpoint — no
        ``surrogate`` key — resumes cleanly; the ctor's gate starts
        fresh from its own state."""
        path = str(tmp_path / "ck.json")
        eng = AsyncEvolution(_pop(), max_in_flight=1, seed=5,
                             checkpoint_every=4)
        eng.set_fault_injector(FaultInjector(FaultPlan([
            FaultSpec(hook="master_boundary", kind="kill_master", at=2)])))
        with pytest.raises(MasterKilled):
            eng.run(max_evaluations=24, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        state["schema_version"] = 3
        state.pop("surrogate", None)
        json.dump(state, open(path, "w"))
        eng2 = AsyncEvolution(_pop(), max_in_flight=1, seed=5)
        eng2.run(max_evaluations=24, checkpointer=Checkpointer(path))
        assert eng2.completed == 24

    def test_newer_schema_refused(self, tmp_path):
        path = str(tmp_path / "ck.json")
        json.dump({"schema_version": 5}, open(path, "w"))
        with pytest.raises(ValueError, match="newer"):
            Checkpointer(path).load()

    def test_gate_status_in_ops_status(self):
        eng, gate = _gated()
        eng.run(max_evaluations=24)
        status = eng._ops_status()["surrogate"]
        assert status["admitted"] == gate.admitted
        assert status["trained"] is True
