"""Bench bookkeeping tests (no TPU, no model runs).

The measurement itself runs on the real chip (driver-invoked); these pin
the pure logic around it: the analytic FLOPs model's inputs and the
round-over-round delta reporting (VERDICT r2 item 7 — a throughput-up/
accuracy-down trade must be visible on the bench line).
"""

import importlib
import json
import sys

import pytest

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import bench  # noqa: E402


def test_prev_round_deltas_reports_ratios(tmp_path):
    prev = {
        "parsed": {
            "value": 100.0,
            "accuracy": {"proxy_mean": 0.6},
            "full_schedule": {"individuals_per_hour_per_chip": 10.0, "accuracy_mean": 0.99},
        }
    }
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(prev))
    record = {
        "value": 150.0,
        "accuracy": {"proxy_mean": 0.55},
        "full_schedule": {"individuals_per_hour_per_chip": 12.0, "accuracy_mean": 0.95},
    }
    deltas = bench.prev_round_deltas(record, base_dir=str(tmp_path))
    assert deltas["r01"]["throughput_ratio"] == pytest.approx(1.5)
    assert deltas["r01"]["proxy_accuracy_delta"] == pytest.approx(-0.05)
    assert deltas["r01"]["full_throughput_ratio"] == pytest.approx(1.2)
    assert deltas["r01"]["full_accuracy_delta"] == pytest.approx(-0.04)


def test_prev_round_deltas_survives_malformed_artifacts(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text("{not json")
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({"parsed": {}}))
    assert bench.prev_round_deltas(
        {"value": 1.0, "accuracy": {"proxy_mean": 0.5}}, base_dir=str(tmp_path)
    ) == {}


def test_repo_artifacts_parse_against_current_record_shape():
    """The committed BENCH_r*.json files must keep satisfying the reader."""
    importlib.reload(bench)
    record = {
        "value": 20000.0,
        "accuracy": {"proxy_mean": 0.63},
        "full_schedule": {"individuals_per_hour_per_chip": 250.0, "accuracy_mean": 0.99},
    }
    deltas = bench.prev_round_deltas(record)
    # r01 and r02 exist in the repo; r02 has full_schedule fields, r01 not
    assert "r01" in deltas and "r02" in deltas
    assert "full_throughput_ratio" in deltas["r02"]
    assert "throughput_ratio" in deltas["r01"]


def test_flops_model_matches_schedule_shape():
    """schedule_flops scales linearly in pop and epochs (sanity pins)."""
    f1 = bench.schedule_flops(bench.PROXY, pop=10)
    f2 = bench.schedule_flops(bench.PROXY, pop=20)
    assert f2 == pytest.approx(2 * f1)
    # doubling epochs doubles the train term but not the eval term
    more_epochs = dict(bench.PROXY, epochs=(2,))
    assert 1.4 * f1 < bench.schedule_flops(more_epochs, 10) < 2 * f1
