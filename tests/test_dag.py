"""Tests for the Genetic-CNN DAG decode (ops/dag.py).

SURVEY.md §7 step 2 calls for exhaustive decode checks at small stage sizes:
for k=3 there are 2**3 = 8 graphs, enumerable by hand.
"""

import itertools

import numpy as np
import pytest

from gentun_tpu.ops.dag import (
    StageMasks,
    adjacency_to_bits,
    bits_to_adjacency,
    canonical_key,
    decode_genome,
    decode_stage,
    stack_genome_masks,
    triangular_index,
)


class TestTriangularIndex:
    def test_ordering_matches_paper_grouping(self):
        # Bits grouped by target: (0→1), (0→2), (1→2), (0→3), ...
        assert triangular_index(0, 1) == 0
        assert triangular_index(0, 2) == 1
        assert triangular_index(1, 2) == 2
        assert triangular_index(0, 3) == 3
        assert triangular_index(2, 3) == 5

    def test_rejects_bad_pairs(self):
        with pytest.raises(ValueError):
            triangular_index(2, 2)
        with pytest.raises(ValueError):
            triangular_index(3, 1)

    def test_bijection_with_adjacency(self):
        k = 5
        n_bits = k * (k - 1) // 2
        bits = tuple(int(b) for b in np.random.default_rng(0).integers(0, 2, n_bits))
        adj = bits_to_adjacency(bits, k)
        for i in range(k):
            for j in range(i + 1, k):
                assert adj[i, j] == bits[triangular_index(i, j)]
        assert adjacency_to_bits(adj) == bits


class TestDecodeStageExhaustiveK3:
    """All 8 graphs for k=3, checked against hand-derived expectations."""

    # bits = (b_01, b_02, b_12) → expected (active, entry, exit)
    CASES = {
        (0, 0, 0): ([0, 0, 0], [0, 0, 0], [0, 0, 0]),  # all isolated: identity stage
        (1, 0, 0): ([1, 1, 0], [1, 0, 0], [0, 1, 0]),  # chain 0→1, node 2 isolated
        (0, 1, 0): ([1, 0, 1], [1, 0, 0], [0, 0, 1]),  # chain 0→2
        (0, 0, 1): ([0, 1, 1], [0, 1, 0], [0, 0, 1]),  # chain 1→2
        (1, 1, 0): ([1, 1, 1], [1, 0, 0], [0, 1, 1]),  # fan-out 0→{1,2}
        (1, 0, 1): ([1, 1, 1], [1, 0, 0], [0, 0, 1]),  # path 0→1→2
        (0, 1, 1): ([1, 1, 1], [1, 1, 0], [0, 0, 1]),  # fan-in {0,1}→2
        (1, 1, 1): ([1, 1, 1], [1, 0, 0], [0, 0, 1]),  # full DAG
    }

    @pytest.mark.parametrize("bits", list(CASES))
    def test_masks(self, bits):
        active, entry, exit_ = self.CASES[bits]
        m = decode_stage(bits, 3)
        np.testing.assert_array_equal(m.active, np.float32(active))
        np.testing.assert_array_equal(m.entry, np.float32(entry))
        np.testing.assert_array_equal(m.exit, np.float32(exit_))
        assert m.has_active == (1.0 if any(active) else 0.0)

    def test_all_zero_is_identity_stage(self):
        m = decode_stage((0, 0, 0), 3)
        assert m.has_active == 0.0
        assert m.adj.sum() == 0


class TestDecodeInvariants:
    """Property checks over every k=4 and random k=5 bit-strings."""

    @pytest.mark.parametrize("k", [2, 3, 4])
    def test_exhaustive_invariants(self, k):
        n_bits = k * (k - 1) // 2
        for bits in itertools.product((0, 1), repeat=n_bits):
            self._check(decode_stage(bits, k), bits)

    def test_random_k5(self):
        rng = np.random.default_rng(7)
        for _ in range(200):
            bits = tuple(int(b) for b in rng.integers(0, 2, 10))
            self._check(decode_stage(bits, 5), bits)

    @staticmethod
    def _check(m: StageMasks, bits):
        k = m.k
        # adjacency strictly upper triangular, equals input bits
        assert np.all(np.tril(m.adj) == 0)
        assert adjacency_to_bits(m.adj) == tuple(bits)
        in_deg = m.adj.sum(axis=0)
        out_deg = m.adj.sum(axis=1)
        # every node with any edge is active; isolated nodes inactive
        np.testing.assert_array_equal(m.active, ((in_deg + out_deg) > 0).astype(np.float32))
        # entry/exit only on active nodes
        assert np.all(m.entry <= m.active)
        assert np.all(m.exit <= m.active)
        # active ⇒ reachable: every active non-entry node has an in-edge
        np.testing.assert_array_equal(m.entry, m.active * (in_deg == 0))
        np.testing.assert_array_equal(m.exit, m.active * (out_deg == 0))
        # at least one entry and one exit whenever anything is active
        if m.has_active:
            assert m.entry.sum() >= 1 and m.exit.sum() >= 1
        else:
            assert m.active.sum() == 0


class TestGenomeDecode:
    def test_decode_genome_and_stack(self):
        nodes = (3, 5)
        genomes = [
            {"S_1": (1, 0, 1), "S_2": tuple(int(b) for b in np.random.default_rng(i).integers(0, 2, 10))}
            for i in range(4)
        ]
        masks = decode_genome(genomes[0], nodes)
        assert [m.k for m in masks] == [3, 5]

        stacked = stack_genome_masks(genomes, nodes)
        assert len(stacked) == 2
        assert stacked[0]["adj"].shape == (4, 3, 3)
        assert stacked[1]["adj"].shape == (4, 5, 5)
        assert stacked[0]["entry"].shape == (4, 3)
        assert stacked[1]["has_active"].shape == (4,)
        # stacking preserves per-genome decode
        for p, g in enumerate(genomes):
            per = decode_genome(g, nodes)
            for s in range(2):
                np.testing.assert_array_equal(stacked[s]["adj"][p], per[s].adj)

    def test_missing_gene_raises(self):
        with pytest.raises(KeyError):
            decode_genome({"S_1": (0, 0, 0)}, (3, 5))


class TestCanonicalKey:
    def test_isomorphic_chains_collapse(self):
        # For k=3: single-edge graphs 0→1, 0→2, 1→2 are all "a 2-chain plus
        # an isolated node" — architecturally identical.
        keys = {
            canonical_key({"S_1": bits}, (3,))
            for bits in [(1, 0, 0), (0, 1, 0), (0, 0, 1)]
        }
        assert len(keys) == 1

    def test_distinct_architectures_stay_distinct(self):
        k_chain = canonical_key({"S_1": (1, 0, 1)}, (3,))  # path 0→1→2
        k_fanin = canonical_key({"S_1": (0, 1, 1)}, (3,))  # {0,1}→2
        k_fanout = canonical_key({"S_1": (1, 1, 0)}, (3,))  # 0→{1,2}
        k_empty = canonical_key({"S_1": (0, 0, 0)}, (3,))
        assert len({k_chain, k_fanin, k_fanout, k_empty}) == 4

    def test_canonicalization_is_idempotent_and_valid(self):
        rng = np.random.default_rng(3)
        for _ in range(100):
            bits = tuple(int(b) for b in rng.integers(0, 2, 10))
            key = canonical_key({"S_1": bits}, (5,))
            # canonical bits are themselves a valid genome mapping to itself
            assert canonical_key({"S_1": key[0]}, (5,)) == key

    def test_equivalence_classes_k3_total(self):
        # The 8 k=3 graphs collapse into exactly 6 architecture classes:
        # empty, 2-chain(x3 isomorphs), 3-path, fan-in, fan-out, full DAG.
        keys = {
            canonical_key({"S_1": bits}, (3,))
            for bits in itertools.product((0, 1), repeat=3)
        }
        assert len(keys) == 6
