"""Tests for the masked-supergraph Genetic-CNN fitness model (models/cnn.py).

SURVEY.md §4: the rebuild must supply genome→module decode tests and
single-chip train-step correctness the reference never had.  Everything here
runs on the virtual CPU mesh (conftest pins jax to cpu).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from gentun_tpu.models.cnn import GeneticCnnModel, MaskedGeneticCnn
from gentun_tpu.ops.dag import stack_genome_masks

FAST = dict(
    nodes=(3,),
    kernels_per_layer=(8,),
    kfold=2,
    epochs=(2,),
    learning_rate=(0.05,),
    batch_size=32,
    dense_units=32,
    compute_dtype="float32",
    seed=0,
)


@pytest.fixture(scope="module")
def separable_data():
    """4 classes of 8×8 images with distinct mean patterns — easy to learn."""
    rng = np.random.default_rng(0)
    protos = rng.normal(size=(4, 8, 8, 1)).astype(np.float32)
    y = rng.integers(0, 4, size=192).astype(np.int32)
    x = protos[y] + 0.3 * rng.normal(size=(192, 8, 8, 1)).astype(np.float32)
    return x, y


def _masks_for(genes, nodes):
    return [
        {k: jnp.asarray(v[0]) for k, v in stage.items()}
        for stage in stack_genome_masks([genes], nodes)
    ]


class TestMaskedGeneticCnnForward:
    def test_output_shape_two_stages(self):
        model = MaskedGeneticCnn(
            nodes=(3, 5), filters=(4, 8), dense_units=16, n_classes=10,
            compute_dtype=jnp.float32,
        )
        genes = {"S_1": (1, 0, 1), "S_2": (1,) * 10}
        masks = _masks_for(genes, (3, 5))
        x = jnp.zeros((2, 16, 16, 1))
        params = model.init(jax.random.PRNGKey(0), x, masks)
        out = model.apply(params, x, masks)
        assert out.shape == (2, 10)
        assert out.dtype == jnp.float32

    def test_identity_stage_matches_entry_conv_passthrough(self):
        """All-zero genome ⇒ stage output is the entry conv output, pooled."""
        model = MaskedGeneticCnn(
            nodes=(3,), filters=(4,), dense_units=8, n_classes=2,
            compute_dtype=jnp.float32,
        )
        masks = _masks_for({"S_1": (0, 0, 0)}, (3,))
        x = jnp.ones((1, 8, 8, 1))
        params = model.init(jax.random.PRNGKey(1), x, masks)
        out = model.apply(params, x, masks)
        assert out.shape == (1, 2)
        assert np.isfinite(np.asarray(out)).all()

    def test_inactive_node_gradients_are_zero(self):
        """Masking correctness: a dropped node must not touch the loss.

        Genome (1, 0, 0) has the chain 0→1 and node 2 isolated — every
        gradient of stage0_node2's conv must be exactly zero, while active
        nodes' gradients are not.
        """
        model = MaskedGeneticCnn(
            nodes=(3,), filters=(4,), dense_units=8, n_classes=2,
            compute_dtype=jnp.float32,
        )
        masks = _masks_for({"S_1": (1, 0, 0)}, (3,))
        x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8, 8, 1)), jnp.float32)
        variables = model.init(jax.random.PRNGKey(2), x, masks)

        def loss(params):
            return model.apply({"params": params}, x, masks).sum()

        grads = jax.grad(loss)(variables["params"])
        dead = grads["stage0_node2"]["kernel"]
        live = grads["stage0_node0"]["kernel"]
        assert np.all(np.asarray(dead) == 0.0)
        assert np.any(np.asarray(live) != 0.0)

    def test_isomorphic_genomes_same_program_different_masks(self):
        """1→2 chain vs 2→3 chain: same compiled fn, numerically same loss
        landscape up to parameter relabeling — here we just assert both run
        through one shared program (no retrace) and give finite outputs."""
        model = MaskedGeneticCnn(
            nodes=(3,), filters=(4,), dense_units=8, n_classes=2,
            compute_dtype=jnp.float32,
        )
        x = jnp.ones((1, 8, 8, 1))
        traces = []

        @jax.jit
        def fwd(params, masks):
            traces.append(1)
            return model.apply(params, x, masks)

        m1 = _masks_for({"S_1": (1, 0, 0)}, (3,))
        m2 = _masks_for({"S_1": (0, 0, 1)}, (3,))
        params = model.init(jax.random.PRNGKey(0), x, m1)
        out1 = fwd(params, m1)
        out2 = fwd(params, m2)
        assert len(traces) == 1  # masks are data: one trace serves all genomes
        assert np.isfinite(np.asarray(out1)).all() and np.isfinite(np.asarray(out2)).all()


class TestGeneticCnnModelCV:
    def test_learns_separable_data(self, separable_data):
        x, y = separable_data
        m = GeneticCnnModel(x, y, {"S_1": (1, 0, 1)}, **FAST)
        fit = m.cross_validate()
        assert 0.5 < fit <= 1.0

    def test_population_path_matches_shapes_and_learns(self, separable_data):
        x, y = separable_data
        genomes = [
            {"S_1": (0, 0, 0)},
            {"S_1": (1, 0, 1)},
            {"S_1": (1, 1, 1)},
        ]
        accs = GeneticCnnModel.cross_validate_population(x, y, genomes, **FAST)
        assert accs.shape == (3,)
        assert (accs > 0.4).all()

    def test_flat_input_reshape(self, separable_data):
        x, y = separable_data
        flat = x.reshape(x.shape[0], -1)
        m = GeneticCnnModel(
            flat, y, {"S_1": (1, 0, 1)}, input_shape=(8, 8, 1), **FAST
        )
        assert 0.5 < m.cross_validate() <= 1.0

    def test_compile_cache_no_retrace_across_calls(self, separable_data):
        from gentun_tpu.models.cnn import _fold_segment_fns

        x, y = separable_data
        GeneticCnnModel.cross_validate_population(x, y, [{"S_1": (0, 1, 0)}], **FAST)
        before = _fold_segment_fns.cache_info().hits
        GeneticCnnModel.cross_validate_population(x, y, [{"S_1": (1, 1, 0)}], **FAST)
        after = _fold_segment_fns.cache_info()
        # Identical static config: the segmented-factory must hit its cache
        # (same jitted program family for every genome — SURVEY.md §7 #1).
        assert after.hits > before

    def test_config_validation(self, separable_data):
        x, y = separable_data
        with pytest.raises(TypeError):
            GeneticCnnModel(x, y, {"S_1": (0, 0, 0)}, bogus_knob=3, **FAST).cross_validate()
        with pytest.raises(ValueError):
            GeneticCnnModel(
                x, y, {"S_1": (0, 0, 0)},
                nodes=(3,), kernels_per_layer=(8, 8), kfold=2,
                epochs=(1,), learning_rate=(0.1,), compute_dtype="float32",
            ).cross_validate()
        with pytest.raises(ValueError):  # epochs/lr not parallel
            GeneticCnnModel(
                x, y, {"S_1": (0, 0, 0)},
                nodes=(3,), kernels_per_layer=(8,), kfold=2,
                epochs=(1, 2), learning_rate=(0.1,), compute_dtype="float32",
            ).cross_validate()

    def test_staged_lr_schedule_runs(self, separable_data):
        x, y = separable_data
        m = GeneticCnnModel(
            x, y, {"S_1": (1, 1, 1)},
            nodes=(3,), kernels_per_layer=(8,), kfold=2,
            epochs=(1, 1), learning_rate=(0.05, 0.005),
            batch_size=32, dense_units=32, compute_dtype="float32", seed=1,
        )
        assert 0.0 <= m.cross_validate() <= 1.0


class TestFitnessReps:
    """fitness_reps=R (VERDICT r4 weak #1): per-evaluation fitness averaged
    over R independent trainings, tiled through the population vmap axis."""

    def test_reps_shape_and_agreement_with_per_seed_calls(self, separable_data):
        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (0, 1, 1)}]
        accs = GeneticCnnModel.cross_validate_population(
            x, y, genomes, fitness_reps=2, **FAST
        )
        assert accs.shape == (2,)
        assert np.isfinite(accs).all() and (accs > 0.3).all()
        # Each rep is one full run at a derived seed: the average must
        # reproduce the mean of the explicit per-seed calls exactly.
        base = FAST["seed"]
        per_seed = [
            GeneticCnnModel.cross_validate_population(
                x, y, genomes, **{**FAST, "seed": base + 7919 * r}
            )
            for r in range(2)
        ]
        np.testing.assert_allclose(accs, np.mean(per_seed, axis=0), rtol=1e-6)

    def test_reps_are_independent_trainings(self, separable_data):
        """The derived-seed reps must not be bit-identical replays (they
        vary init, dropout, shuffle and folds), or averaging would remove
        nothing — this is the failure mode that sank the earlier pop-axis
        tiling design under the learned OOM chunk cap."""
        x, y = separable_data
        base = FAST["seed"]
        r0, r1 = (
            GeneticCnnModel.cross_validate_population(
                x, y, [{"S_1": (1, 0, 1)}], **{**FAST, "seed": base + 7919 * r}
            )[0]
            for r in range(2)
        )
        assert r0 != r1, (r0, r1)

    def test_reps_validation_and_instance_path(self, separable_data):
        x, y = separable_data
        with pytest.raises(ValueError):
            GeneticCnnModel.cross_validate_population(
                x, y, [{"S_1": (1, 0, 1)}], fitness_reps=0, **FAST
            )
        m = GeneticCnnModel(x, y, {"S_1": (1, 0, 1)}, fitness_reps=2, **FAST)
        assert 0.4 < m.cross_validate() <= 1.0

    def test_train_and_score_reps(self, separable_data):
        x, y = separable_data
        accs = GeneticCnnModel.train_and_score(
            x[:128], y[:128], x[128:], y[128:], [{"S_1": (1, 0, 1)}],
            fitness_reps=2, **FAST
        )
        assert accs.shape == (1,)
        assert 0.0 <= accs[0] <= 1.0


class TestEntryChannelPad:
    """entry_channel_pad (VERDICT r4 item 5): zero-pad input channels at
    data-prep level so the entry conv kernel lands on lane-aligned shapes;
    all-zero channels contribute nothing to the conv outputs."""

    def test_padded_run_learns_and_shapes_flow(self, separable_data):
        x, y = separable_data  # 1-channel 8x8
        accs = GeneticCnnModel.cross_validate_population(
            x, y, [{"S_1": (1, 0, 1)}], entry_channel_pad=8, **FAST
        )
        assert accs.shape == (1,)
        assert 0.4 < accs[0] <= 1.0

    def test_flat_input_reshapes_with_raw_shape_then_pads(self, separable_data):
        x, y = separable_data
        flat = x.reshape(x.shape[0], -1)
        m = GeneticCnnModel(
            flat, y, {"S_1": (1, 0, 1)}, input_shape=(8, 8, 1),
            entry_channel_pad=4, **{**FAST, "epochs": (4,)}
        )
        assert 0.4 < m.cross_validate() <= 1.0

    def test_pad_no_op_when_channels_already_enough(self, separable_data):
        from gentun_tpu.models.cnn import _normalize_config

        x, y = separable_data
        cfg = _normalize_config(x, y, dict(entry_channel_pad=1))
        assert cfg["input_shape"] == (8, 8, 1)  # pad below C: unchanged
        with pytest.raises(ValueError):
            _normalize_config(x, y, dict(entry_channel_pad=0))


class TestStageExitConv:
    """Optional Xie & Yuille output-node conv (ADVICE r1, cnn.py stage exit)."""

    def test_exit_conv_params_exist_and_forward_works(self):
        model = MaskedGeneticCnn(
            nodes=(3,), filters=(4,), dense_units=8, n_classes=2,
            compute_dtype=jnp.float32, stage_exit_conv=True,
        )
        masks = _masks_for({"S_1": (1, 0, 1)}, (3,))
        x = jnp.ones((2, 8, 8, 1))
        params = model.init(jax.random.PRNGKey(0), x, masks)
        assert "stage0_exit" in params["params"]
        out = model.apply(params, x, masks)
        assert out.shape == (2, 2)
        assert np.isfinite(np.asarray(out)).all()

    def test_population_path_trains_with_exit_conv(self, separable_data):
        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (0, 0, 0)}]
        accs = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **{**FAST, "stage_exit_conv": True}
        )
        assert accs.shape == (2,)
        assert np.isfinite(accs).all()
        assert (accs > 0.25).all()  # beats 4-class chance


class TestTrainAndScore:
    def test_holdout_scores_match_separability(self, separable_data):
        x, y = separable_data
        x_tr, y_tr, x_te, y_te = x[:160], y[:160], x[160:], y[160:]
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (1, 1, 1)}]
        accs = GeneticCnnModel.train_and_score(
            x_tr, y_tr, x_te, y_te, genomes, **FAST
        )
        assert accs.shape == (2,)
        assert np.isfinite(accs).all()
        assert (accs > 0.25).all()  # beats 4-class chance on held-out data

    def test_holdout_single_genome_and_uneven_test(self, separable_data):
        x, y = separable_data
        # test block not divisible by batch_size: exercises padding weights
        accs = GeneticCnnModel.train_and_score(
            x[:150], y[:150], x[150:183], y[150:183], [{"S_1": (1, 0, 1)}], **FAST
        )
        assert accs.shape == (1,)
        assert 0.0 <= float(accs[0]) <= 1.0


class TestSegmentedExecution:
    """Default executor: host loop of bounded device calls (watchdog-safe)."""

    def test_segmented_matches_fused_exactly(self, separable_data):
        """Same schedule, same seeds: segmented (any segment size) and the
        fused single-program path must produce identical accuracies."""
        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (1, 1, 1)}]
        fused = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **{**FAST, "fold_parallel": True}
        )
        seg_big = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **{**FAST, "segment_steps": None}
        )
        seg_tiny = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **{**FAST, "segment_steps": 2}
        )
        np.testing.assert_allclose(seg_big, seg_tiny, atol=1e-5)
        np.testing.assert_allclose(fused, seg_big, atol=1e-4)

    def test_segment_bounds(self):
        from gentun_tpu.models.cnn import _segment_bounds

        assert _segment_bounds(10, None) == [(0, 10)]
        assert _segment_bounds(10, 96) == [(0, 10)]
        assert _segment_bounds(10, 4) == [(0, 4), (4, 8), (8, 10)]
        assert _segment_bounds(8, 4) == [(0, 4), (4, 8)]


class TestPopBucketing:
    def test_bucket_function(self):
        from gentun_tpu.models.cnn import _pop_bucket

        # floor is 2: the singleton program is numerically distinct (purity)
        assert [_pop_bucket(n) for n in (1, 2, 3, 5, 8, 9, 15)] == [2, 2, 4, 8, 8, 16, 16]
        assert _pop_bucket(16) == 16 and _pop_bucket(20) == 20  # large = exact

    def test_small_batches_share_compiled_shape(self, separable_data):
        """Sizes 3 and 4 pad to the same bucket (4): the segmented factory's
        jitted fns see one shape, so the second call cannot retrace."""
        x, y = separable_data
        g = lambda bits: {"S_1": bits}
        a3 = GeneticCnnModel.cross_validate_population(
            x, y, [g((1, 0, 1)), g((0, 1, 0)), g((1, 1, 0))], **FAST
        )
        a4 = GeneticCnnModel.cross_validate_population(
            x, y, [g((1, 0, 1)), g((0, 1, 0)), g((1, 1, 0)), g((1, 1, 1))], **FAST
        )
        assert a3.shape == (3,) and a4.shape == (4,)
        # padding is invisible: shared genomes score identically across calls
        np.testing.assert_allclose(a3, a4[:3], atol=1e-5)

    def test_padding_disabled_keeps_exact_size(self, separable_data):
        x, y = separable_data
        accs = GeneticCnnModel.cross_validate_population(
            x, y, [{"S_1": (1, 0, 1)}] * 3, **{**FAST, "pop_padding": False}
        )
        assert accs.shape == (3,)


class TestDeviceDatasetCache:
    def test_cache_hits_across_calls_even_with_conversion(self, separable_data):
        """The cache keys on the CALLER's arrays, so flat inputs (reshaped
        fresh every call by _prepare_data) still hit."""
        from gentun_tpu.models import cnn as cnn_mod

        x, y = separable_data
        flat = np.ascontiguousarray(x.reshape(x.shape[0], -1))  # stable caller object
        cnn_mod._DATASET_CACHE.clear()
        cfg = {**FAST, "input_shape": (8, 8, 1)}
        GeneticCnnModel.cross_validate_population(flat, y, [{"S_1": (1, 0, 1)}], **cfg)
        assert len(cnn_mod._DATASET_CACHE) == 1
        (xref, yref, xd, yd) = next(iter(cnn_mod._DATASET_CACHE.values()))
        GeneticCnnModel.cross_validate_population(flat, y, [{"S_1": (0, 1, 0)}], **cfg)
        assert len(cnn_mod._DATASET_CACHE) == 1
        (xref2, yref2, xd2, yd2) = next(iter(cnn_mod._DATASET_CACHE.values()))
        assert xd2 is xd  # same device copy reused, no re-upload
        assert xref() is flat

    def test_dead_entries_evicted_on_lookup(self):
        from gentun_tpu.models import cnn as cnn_mod

        cnn_mod._DATASET_CACHE.clear()
        rng = np.random.default_rng(0)
        xa = rng.normal(size=(64, 8, 8, 1)).astype(np.float32)
        ya = rng.integers(0, 2, size=64).astype(np.int32)
        GeneticCnnModel.cross_validate_population(xa, ya, [{"S_1": (1, 0, 1)}], **FAST)
        assert len(cnn_mod._DATASET_CACHE) == 1
        del xa  # host array dies → entry must be evicted on next lookup
        xb = rng.normal(size=(64, 8, 8, 1)).astype(np.float32)
        yb = rng.integers(0, 2, size=64).astype(np.int32)
        GeneticCnnModel.cross_validate_population(xb, yb, [{"S_1": (1, 0, 1)}], **FAST)
        assert len(cnn_mod._DATASET_CACHE) == 1  # dead entry gone, live one present
        (xref, *_rest) = next(iter(cnn_mod._DATASET_CACHE.values()))
        assert xref() is xb


def test_eval_batch_size_properties():
    from gentun_tpu.models.cnn import _eval_batch_size

    for bs in (32, 128, 256):
        for n_val in (0, 1, bs - 1, bs, bs + 1, 4 * bs, 4 * bs + 1, 513, 5000):
            eval_bs, nvp = _eval_batch_size(bs, n_val)
            assert nvp >= n_val
            if n_val == 0:
                assert nvp == 0
                continue
            assert nvp % eval_bs == 0  # eval scan covers the block exactly
            assert eval_bs <= 4 * bs  # the documented eval-width bound
            # padding never exceeds one train batch + segment rounding
            assert nvp - n_val < bs + int(np.ceil(nvp / eval_bs))
    # the reviewer's unlucky case: fold 513 @ batch 128 wastes ≤ one batch
    eval_bs, nvp = _eval_batch_size(128, 513)
    assert nvp == 640 and eval_bs == 320


class TestOomChunking:
    """Deep configs (BASELINE #5) OOM a single chip when the whole
    population vmaps through one program; the evaluator must self-heal by
    chunking and remember the cap for the config."""

    def _fake_oom_run(self, fail_above):
        calls = []

        def run(genomes):
            calls.append(len(genomes))
            if len(genomes) > fail_above:
                raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating ...")
            return np.asarray([float(sum(g["S_1"])) for g in genomes])

        return run, calls

    def test_splits_on_oom_and_remembers_cap(self):
        from gentun_tpu.models import cnn as cnn_mod

        key = ("test-cfg-a",)
        cnn_mod._POP_PROGRAM_CAP.pop(key, None)
        run, calls = self._fake_oom_run(fail_above=16)
        genomes = [{"S_1": (1, 0, 1)} for _ in range(50)]
        out = cnn_mod._chunked_by_cap(run, genomes, key)
        assert out.shape == (50,) and (out == 2.0).all()
        # one failed 50-wide attempt, then power-of-two chunks (16s + tail)
        assert calls[0] == 50
        assert all(c <= 16 for c in calls[1:])
        assert cnn_mod._POP_PROGRAM_CAP[key] == 16
        # second call pre-chunks without re-discovering the OOM
        calls.clear()
        out2 = cnn_mod._chunked_by_cap(run, genomes, key)
        assert out2.shape == (50,) and 50 not in calls
        cnn_mod._POP_PROGRAM_CAP.pop(key, None)

    def test_non_oom_errors_propagate(self):
        from gentun_tpu.models import cnn as cnn_mod

        def run(genomes):
            raise ValueError("bad genome")

        with pytest.raises(ValueError, match="bad genome"):
            cnn_mod._chunked_by_cap(run, [{"S_1": (1,)}] * 4, ("test-cfg-b",))
        assert ("test-cfg-b",) not in cnn_mod._POP_PROGRAM_CAP

    def test_single_genome_oom_reraises(self):
        from gentun_tpu.models import cnn as cnn_mod

        def run(genomes):
            raise RuntimeError("RESOURCE_EXHAUSTED")

        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            cnn_mod._chunked_by_cap(run, [{"S_1": (1,)}], ("test-cfg-c",))

    def test_single_genome_oom_falls_back_to_exact_runner(self):
        """The compile bucket floors at 2, so a singleton OOM must retry
        via the UNPADDED runner (a genuinely 1-wide program) — and the
        learned cap=1 must route straight there on later generations."""
        from gentun_tpu.models import cnn as cnn_mod

        calls = []

        def run(genomes):
            calls.append(("padded", len(genomes)))
            raise RuntimeError("RESOURCE_EXHAUSTED")

        def run_exact(genomes):
            calls.append(("exact", len(genomes)))
            return np.full(len(genomes), 0.5, dtype=np.float32)

        key = ("test-cfg-exact",)
        try:
            got = cnn_mod._chunked_by_cap(run, [{"S_1": (1,)}], key, run_exact)
            assert got.tolist() == [0.5]
            assert calls == [("padded", 1), ("exact", 1)]
            assert cnn_mod._POP_PROGRAM_CAP[key] == 1
            # cap remembered: the padded runner is never tried again
            cnn_mod._chunked_by_cap(run, [{"S_1": (1,)}, {"S_1": (0,)}], key, run_exact)
            assert calls[2:] == [("exact", 1), ("exact", 1)]
        finally:
            cnn_mod._POP_PROGRAM_CAP.pop(key, None)

    def test_chunked_matches_manual_chunks_real_model(self, separable_data):
        """A capped run equals evaluating the same chunks directly — AND
        equals the unchunked run: PRNG keys are content-derived
        (``_genome_hashes``), so chunking cannot move any fitness
        (``TestBatchCompositionPurity``)."""
        from gentun_tpu.models import cnn as cnn_mod
        from gentun_tpu.models.cnn import GeneticCnnModel

        x, y = separable_data
        genomes = [{"S_1": (1, 0, 0)}, {"S_1": (0, 1, 1)}, {"S_1": (1, 1, 1)}]
        cfg = dict(nodes=(3,), kernels_per_layer=(8,), dense_units=32,
                   kfold=2, epochs=(1,), learning_rate=(0.05,),
                   batch_size=32, compute_dtype="float32", seed=0)
        unchunked = np.asarray(
            GeneticCnnModel.cross_validate_population(x, y, genomes, **cfg)
        )
        want = np.concatenate([
            np.asarray(GeneticCnnModel.cross_validate_population(x, y, genomes[:2], **cfg)),
            np.asarray(GeneticCnnModel.cross_validate_population(x, y, genomes[2:], **cfg)),
        ])
        np.testing.assert_array_equal(want, unchunked)
        key = cnn_mod._oom_cap_key(cnn_mod._normalize_config(x, y, dict(cfg)))
        cnn_mod._POP_PROGRAM_CAP[key] = 2  # force chunking: 2 + 1
        try:
            got = GeneticCnnModel.cross_validate_population(x, y, genomes, **cfg)
        finally:
            cnn_mod._POP_PROGRAM_CAP.pop(key, None)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


class TestBatchCompositionPurity:
    """Fitness is a pure function of (architecture, config, seed).

    ``_genome_hashes`` folds each slot's PRNG keys from genome content, so
    WHERE an architecture trains — slot index, batch composition,
    compile-bucket shape, alone or among others — cannot change its
    fitness.  This is the property the speculative-fill trajectory-identity
    claim and the cross-run fitness store both rest on (round-5 tailgen
    study measured a diverged search before this fix).

    The cross-bucket assertions below are EXACT on purpose: the suite is
    pinned to CPU (conftest), where XLA's different-program-shape
    compilations round identically, so any inequality here is an RNG
    regression, never float noise.  On TPU the same comparison may flip a
    rare validation sample across program shapes (PERF.md "Tail
    generations") — these tests are not meant to run there."""

    def test_fitness_invariant_to_slot_batch_and_bucket(self, separable_data):
        x, y = separable_data
        g = lambda bits: {"S_1": bits}
        a, b, c = g((1, 0, 1)), g((0, 1, 0)), g((1, 1, 1))
        batch = GeneticCnnModel.cross_validate_population(x, y, [a, b, c], **FAST)  # bucket 4
        alone = GeneticCnnModel.cross_validate_population(x, y, [b], **FAST)        # bucket 2
        swapped = GeneticCnnModel.cross_validate_population(
            x, y, [c, b, a, b, a], **FAST                                          # bucket 8
        )
        # exact equality: the per-slot streams are content-derived and the
        # per-slot math is slot-local, so not even float rounding may move
        assert alone[0] == batch[1]
        assert (swapped[0], swapped[1], swapped[2]) == (batch[2], batch[1], batch[0])
        assert swapped[3] == batch[1] and swapped[4] == batch[0]  # in-batch twins too

    def test_cross_session_packed_window_matches_solo_runs(self, separable_data):
        """The correctness gate for cross-session window packing (ISSUE
        19): two tenants' genomes interleaved slot-by-slot in ONE packed
        device window score EXACTLY what each tenant's solo windows score.
        This is the same purity invariant as above — batch composition is
        not a fitness input — asserted in the shape the broker's packer
        actually produces: a DRR-interleaved window of jobs from different
        sessions sharing one compile envelope."""
        x, y = separable_data
        g = lambda bits: {"S_1": bits}
        sess_a = [g((1, 0, 1)), g((0, 1, 0))]
        sess_b = [g((1, 1, 0)), g((0, 0, 1))]
        # One packed window, tenants interleaved: [a0, b0, a1, b1].
        packed = GeneticCnnModel.cross_validate_population(
            x, y, [sess_a[0], sess_b[0], sess_a[1], sess_b[1]], **FAST)
        solo_a = GeneticCnnModel.cross_validate_population(x, y, sess_a, **FAST)
        solo_b = GeneticCnnModel.cross_validate_population(x, y, sess_b, **FAST)
        assert (packed[0], packed[2]) == (solo_a[0], solo_a[1])
        assert (packed[1], packed[3]) == (solo_b[0], solo_b[1])

    def test_hashes_are_content_not_position(self):
        from gentun_tpu.models.cnn import _genome_hashes

        g1 = {"S_1": (1, 0, 1), "S_2": (0, 1, 1, 0, 0, 1)}
        g2 = {"S_1": (0, 1, 1), "S_2": (0, 1, 1, 0, 0, 1)}
        h = _genome_hashes([g1, g2, g1])
        assert h.shape == (3, 2) and h.dtype == np.uint32  # 64 bits as two words
        assert tuple(h[0]) == tuple(h[2]) != tuple(h[1])
        # order of evaluation / position in the list is irrelevant
        assert tuple(_genome_hashes([g2, g1])[1]) == tuple(h[0])

    def test_key_stream_domains_are_separated(self):
        """Init, CV-train, and holdout streams must never collide for one
        (seed, genome) — without the domain folds, train_and_score under
        the search's own seed would replicate CV fold-0 bit-for-bit and
        correlate the holdout estimate with the CV estimate it checks.
        Driven through the production constants and the production init
        path, not re-derived folds."""
        from gentun_tpu.models import cnn as cnn_mod
        from gentun_tpu.models.cnn import (
            MaskedGeneticCnn, _content_keys, _genome_hashes, _init_population_params,
        )

        assert cnn_mod._INIT_DOMAIN and cnn_mod._HOLDOUT_DOMAIN and (
            cnn_mod._INIT_DOMAIN != cnn_mod._HOLDOUT_DOMAIN
        )
        base = jax.random.PRNGKey(0)
        h = _genome_hashes([{"S_1": (1, 0, 1)}])
        train = np.asarray(_content_keys(base, 1, h))  # CV train keys, fold 0
        init = np.asarray(_content_keys(jax.random.fold_in(base, cnn_mod._INIT_DOMAIN), 1, h))
        holdout = np.asarray(_content_keys(
            jax.random.fold_in(base, cnn_mod._HOLDOUT_DOMAIN), 1, h))
        assert not (train == init).all()
        assert not (train == holdout).all()
        assert not (init == holdout).all()

        # and the init entry point honors domain=: CV-init params vs
        # holdout-init params differ for the same (seed, genome)
        model = MaskedGeneticCnn(nodes=(3,), filters=(4,), dense_units=8,
                                 n_classes=2, compute_dtype=jnp.float32)
        masks = [{k: v for k, v in stage.items()}
                 for stage in stack_genome_masks([{"S_1": (1, 0, 1)}], (3,))]
        cv_params = _init_population_params(model, masks, (8, 8, 1), 1, 1, 0, h)
        ho_params = _init_population_params(model, masks, (8, 8, 1), 1, 1, 0, h,
                                            domain=cnn_mod._HOLDOUT_DOMAIN)
        leaves_cv = jax.tree.leaves(cv_params)
        leaves_ho = jax.tree.leaves(ho_params)
        assert any(not np.array_equal(a, b) for a, b in zip(leaves_cv, leaves_ho))
