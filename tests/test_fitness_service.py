"""Networked shared fitness-memoization service (distributed/fitness_service.py).

The file store already carries measurements across runs; the service
promotes it to a network cache shared by concurrent searches and elastic
fleets.  These tests cover the wire contract (content addressing, version
skew → 409, LRU), the degradation boundary (a dead service must cost
misses, never exceptions), the ServiceBackedCache layering semantics, and
the file store's concurrent-writer safety the service builds on.
"""

import json
import multiprocessing
import os
import time

import pytest

from gentun_tpu.distributed.fitness_service import (
    FitnessService,
    FitnessServiceClient,
    ServiceBackedCache,
    parse_cache_url,
    wire_key,
)
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.utils.fitness_store import (
    FITNESS_PROTOCOL,
    STORE_VERSION,
    key_digest,
    load_fitness_cache,
    save_fitness_cache,
)


class _ListSink:
    def __init__(self):
        self.records = []

    def record(self, rec):
        self.records.append(rec)


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


@pytest.fixture
def service():
    svc = FitnessService(port=0, max_entries=100)
    svc.start()
    yield svc
    svc.stop()


KEY = (("genes", (1, 0, 1)), (("epochs", 2), ("kfold", 3)))
KEY2 = (("genes", (0, 1, 0)), (("epochs", 2), ("kfold", 3)))


class TestWireKey:
    def test_digest_is_64_bit_hex(self):
        d = key_digest(KEY)
        assert len(d) == 16
        int(d, 16)  # hex

    def test_wire_key_carries_fidelity_fingerprint(self):
        # Same genes, different fidelity → different service addresses:
        # a proxy measurement can never answer a full-schedule lookup.
        proxy = (("genes", (1, 0, 1)), (("epochs", 1), ("kfold", 2)))
        full = (("genes", (1, 0, 1)), (("epochs", 20), ("kfold", 5)))
        assert wire_key(proxy) != wire_key(full)
        assert ":" in wire_key(proxy)

    def test_unserializable_key_is_none(self):
        assert wire_key((("blob", b"\x00"),)) is None

    def test_stable_across_processes(self):
        # The address is a pure function of the key — no per-process salt.
        assert wire_key(KEY) == wire_key(tuple(KEY))


class TestParseCacheUrl:
    def test_good_urls_normalize(self):
        assert parse_cache_url("http://10.0.0.2:9736/") == "http://10.0.0.2:9736"

    @pytest.mark.parametrize("bad", [
        "10.0.0.2:9736",          # no scheme
        "ftp://host:21",           # wrong scheme
        "http://host",             # no port
        "http://:9736",            # no host
        "http://host:9736/path",   # path
        "http://host:9736?x=1",    # query
    ])
    def test_bad_urls_raise(self, bad):
        with pytest.raises(ValueError):
            parse_cache_url(bad)


class TestServiceWire:
    def test_lookup_and_publish_roundtrip(self, service):
        c = FitnessServiceClient(service.url)
        wk = wire_key(KEY)
        assert c.lookup([wk]) == {}
        c.publish([(wk, 0.5)])
        assert c.flush(5.0)
        assert c.lookup([wk]) == {wk: 0.5}
        c.close()

    def test_cross_client_sharing(self, service):
        # The point of the service: run B sees what run A measured.
        a, b = FitnessServiceClient(service.url), FitnessServiceClient(service.url)
        a.publish([(wire_key(KEY), 0.9)])
        assert a.flush(5.0)
        assert b.lookup([wire_key(KEY)]) == {wire_key(KEY): 0.9}
        a.close(), b.close()

    def test_lru_eviction_bounded(self):
        svc = FitnessService(port=0, max_entries=3)
        svc.start()
        try:
            c = FitnessServiceClient(svc.url)
            for i in range(5):
                c.publish([(f"{i:016x}:", float(i))])
                assert c.flush(5.0)
            st = svc.stats()
            assert st["entries"] == 3
            assert st["evictions"] == 2
            # Coldest entries went first.
            assert c.lookup(["0" * 16 + ":"]) == {}
            assert c.lookup([f"{4:016x}:"]) != {}
            c.close()
        finally:
            svc.stop()

    def test_lookup_refreshes_lru_position(self):
        svc = FitnessService(port=0, max_entries=2)
        svc.start()
        try:
            c = FitnessServiceClient(svc.url)
            c.publish([("a" * 16 + ":", 1.0), ("b" * 16 + ":", 2.0)])
            assert c.flush(5.0)
            # Touch "a", then insert a third: "b" (now coldest) evicts.
            assert c.lookup(["a" * 16 + ":"])
            c.publish([("c" * 16 + ":", 3.0)])
            assert c.flush(5.0)
            assert c.lookup(["a" * 16 + ":"]) != {}
            assert c.lookup(["b" * 16 + ":"]) == {}
            c.close()
        finally:
            svc.stop()

    def test_version_skew_is_409_and_degrades(self, service):
        # A mismatched client must be refused (all-writers-upgrade-together,
        # enforced at the wire) and must degrade, not crash.
        import urllib.request

        body = json.dumps({"v": 1, "version": STORE_VERSION + 1,
                           "protocol": FITNESS_PROTOCOL,
                           "keys": ["00" * 8 + ":"]}).encode()
        req = urllib.request.Request(
            service.url + "/v1/lookup", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 409
        refusal = json.loads(ei.value.read().decode())
        assert refusal["version"] == STORE_VERSION
        assert refusal["client_version"] == STORE_VERSION + 1

    def test_statusz_serves_counters(self, service):
        import urllib.request

        c = FitnessServiceClient(service.url)
        c.publish([(wire_key(KEY), 0.25)])
        assert c.flush(5.0)
        c.lookup([wire_key(KEY), wire_key(KEY2)])
        with urllib.request.urlopen(service.url + "/statusz", timeout=5) as r:
            st = json.loads(r.read().decode())
        assert st["puts"] == 1 and st["hits"] == 1 and st["misses"] == 1
        c.close()


class TestDegradation:
    def test_dead_service_costs_misses_never_exceptions(self):
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        c = FitnessServiceClient("http://127.0.0.1:1", timeout=0.2, cooldown=30.0)
        assert c.lookup([wire_key(KEY)]) == {}
        c.publish([(wire_key(KEY), 0.5)])  # must not raise
        assert not c.flush(1.0)  # can't drain to a dead service
        assert c.degraded
        # ONE degraded event per transition, with the url.
        evs = [r for r in sink.records
               if r.get("type") == "event" and r["name"] == "fitness_service_degraded"]
        assert len(evs) == 1
        assert evs[0]["data"]["url"] == "http://127.0.0.1:1"
        assert get_registry().counter("fitness_service_degraded_total").value == 1
        c.close(flush_timeout=0.1)

    def test_cooldown_prevents_per_genome_timeouts(self):
        c = FitnessServiceClient("http://127.0.0.1:1", timeout=0.2, cooldown=60.0)
        c.lookup(["a" * 16 + ":"])  # pays the one connect failure
        t0 = time.monotonic()
        for _ in range(50):
            c.lookup(["b" * 16 + ":"])  # inside the cooldown: no socket touch
        assert time.monotonic() - t0 < 0.5
        c.close(flush_timeout=0.1)

    def test_recovery_after_cooldown(self):
        svc = FitnessService(port=0)
        svc.start()
        try:
            url = svc.url
            c = FitnessServiceClient(url, timeout=1.0, cooldown=0.1)
            svc.stop()
            assert c.lookup([wire_key(KEY)]) == {}
            assert c.degraded
            # Restart on the same port; after the cooldown the client heals.
            host, port = svc.address
            svc2 = FitnessService(host=host, port=port)
            svc2.start()
            try:
                svc2.publish([[wire_key(KEY), 0.75]])
                time.sleep(0.15)
                assert c.lookup([wire_key(KEY)]) == {wire_key(KEY): 0.75}
                assert not c.degraded
            finally:
                svc2.stop()
            c.close(flush_timeout=0.1)
        finally:
            try:
                svc.stop()
            except Exception:
                pass


class TestServiceBackedCache:
    def test_read_through_adopts_hit_locally(self, service):
        publisher = FitnessServiceClient(service.url)
        publisher.publish([(wire_key(KEY), 0.6)])
        assert publisher.flush(5.0)
        cache = ServiceBackedCache(FitnessServiceClient(service.url))
        assert KEY in cache
        assert cache[KEY] == 0.6
        # Adopted: the second touch is a plain dict read (no RTT) — the
        # service-side hit counter must not move again.
        before = service.stats()["hits"]
        assert cache.get(KEY) == 0.6
        assert service.stats()["hits"] == before
        publisher.close(), cache.client.close()

    def test_write_publishes_for_the_next_run(self, service):
        cache = ServiceBackedCache(FitnessServiceClient(service.url))
        cache[KEY] = 0.8
        assert cache.client.flush(5.0)
        other = ServiceBackedCache(FitnessServiceClient(service.url))
        assert other.get(KEY) == 0.8
        cache.client.close(), other.client.close()

    def test_local_miss_and_service_miss_is_keyerror(self, service):
        cache = ServiceBackedCache(FitnessServiceClient(service.url))
        assert KEY2 not in cache
        assert cache.get(KEY2, -1.0) == -1.0
        with pytest.raises(KeyError):
            cache[KEY2]
        cache.client.close()

    def test_rebase_keeps_service_backing(self, service):
        # Checkpoint resume replaces the cache contents; the service layer
        # must survive (the load_state_dict paths call rebase()).
        publisher = FitnessServiceClient(service.url)
        publisher.publish([(wire_key(KEY), 0.4)])
        assert publisher.flush(5.0)
        cache = ServiceBackedCache(FitnessServiceClient(service.url))
        cache.rebase({KEY2: 1.5})
        assert dict.__len__(cache) == 1  # local contents replaced
        assert cache.get(KEY) == 0.4  # but the service still answers
        publisher.close(), cache.client.close()

    def test_seed_dict_wins_over_service(self, service):
        publisher = FitnessServiceClient(service.url)
        publisher.publish([(wire_key(KEY), 99.0)])
        assert publisher.flush(5.0)
        cache = ServiceBackedCache(FitnessServiceClient(service.url), {KEY: 0.1})
        assert cache[KEY] == 0.1  # local-first
        publisher.close(), cache.client.close()

    def test_unserializable_keys_stay_local_only(self, service):
        cache = ServiceBackedCache(FitnessServiceClient(service.url))
        k = (("blob", b"\x00"),)
        cache[k] = 2.0
        assert cache[k] == 2.0
        assert cache.client.flush(2.0)
        assert service.stats()["entries"] == 0  # never reached the wire
        cache.client.close()

    def test_degraded_cache_behaves_like_plain_dict(self):
        cache = ServiceBackedCache(
            FitnessServiceClient("http://127.0.0.1:1", timeout=0.2, cooldown=60.0))
        cache[KEY] = 0.3
        assert cache[KEY] == 0.3
        assert KEY2 not in cache
        cache.client.close(flush_timeout=0.1)


def _writer_proc(path, start, stop, lo):
    """Append 200 distinct v3 triples, racing the sibling process."""
    # Config-free keys all stamp the same empty-config fingerprint,
    # keeping the test focused on file-level atomicity.
    start.wait(10)
    for i in range(lo, lo + 200):
        save_fitness_cache({(("g", i),): float(i)}, path)
    stop.set()


class TestConcurrentStoreWriters:
    def test_two_processes_append_without_corruption(self, tmp_path):
        # The service's durability story still rests on the file store's
        # read-merge-write-under-flock cycle: two processes hammering the
        # same store must union cleanly — no lost entries, no quarantine.
        path = str(tmp_path / "store.json")
        ctx = multiprocessing.get_context("spawn")
        start = ctx.Event()
        stops = [ctx.Event(), ctx.Event()]
        procs = [
            ctx.Process(target=_writer_proc, args=(path, start, stops[0], 0)),
            ctx.Process(target=_writer_proc, args=(path, start, stops[1], 1000)),
        ]
        for p in procs:
            p.start()
        start.set()
        for p in procs:
            p.join(timeout=120)
            assert p.exitcode == 0
        assert not os.path.exists(path + ".corrupt")
        cache = load_fitness_cache(path)
        assert len(cache) == 400  # both writers' entries all survived
        assert cache[(("g", 5),)] == 5.0
        assert cache[(("g", 1005),)] == 1005.0
        with open(path) as fh:
            raw = json.load(fh)
        assert raw["version"] == STORE_VERSION
        # v3 triples: [key, fitness, fingerprint].
        assert all(len(t) == 3 for t in raw["entries"])

    def test_fingerprint_mismatch_still_dropped_after_merge(self, tmp_path):
        # The recompute path must survive concurrent merging: a tampered
        # fingerprint is dropped on load (forcing a retrain), not trusted.
        path = str(tmp_path / "store.json")
        key = (("g", 1), (("epochs", 2),))
        save_fitness_cache({key: 1.0}, path)
        save_fitness_cache({(("g", 2),): 2.0}, path)  # a merge cycle on top
        with open(path) as fh:
            raw = json.load(fh)
        for triple in raw["entries"]:
            if triple[0] == [["g", 1], [["epochs", 2]]]:
                triple[2] = "0" * 12  # tamper that key's fingerprint
        with open(path, "w") as fh:
            json.dump(raw, fh)
        cache = load_fitness_cache(path)
        assert key not in cache  # mismatch → recompute
        assert (("g", 2),) in cache  # untampered survives
