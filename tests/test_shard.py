"""Horizontal broker sharding: ring placement, multi-homed workers,
sharded masters (DISTRIBUTED.md "Horizontal broker sharding").

Covers the consistent-hash ring's contracts (deterministic cross-process
placement, vnode balance, minimal movement on membership change), the
per-connection reconnect backoff regression (a flapping shard must
inflate only its OWN delay), multi-homed credit conservation across two
live shards with a mid-run drain, the ``SessionClient`` router mode, and
the end-to-end equality proof: a 2-shard ``DistributedPopulation`` GA
run lands bit-identical to the single-broker reference.
"""

import socket
import threading
import time

import numpy as np
import pytest

from gentun_tpu import GeneticAlgorithm, Individual, genetic_cnn_genome
from gentun_tpu.distributed import DistributedPopulation, GentunClient, JobBroker
from gentun_tpu.distributed.sessions import SessionClient
from gentun_tpu.distributed.shard import (
    ShardedBroker,
    ShardRing,
    ShardRouter,
    parse_broker_urls,
    shard_id,
)
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry


class OneMax(Individual):
    """Pure function of genes: sharded and single-broker evaluation agree
    bit-for-bit, so the equality proofs below are exact."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


class CountingOneMax(OneMax):
    """Slow enough that a drain lands mid-run, and every evaluate() call
    is tallied — the exactly-once ledger for the credit-conservation
    test."""

    calls = []
    _lock = threading.Lock()

    def evaluate(self):
        time.sleep(0.1)
        with CountingOneMax._lock:
            CountingOneMax.calls.append(1)
        return super().evaluate()


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _wait(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _spawn_multihome_worker(species, urls, worker_id, capacity=1,
                            prefetch_depth=None):
    stop = threading.Event()
    client = GentunClient(
        species, *DATA, broker_urls=list(urls), capacity=capacity,
        prefetch_depth=prefetch_depth, worker_id=worker_id,
        heartbeat_interval=0.2, reconnect_delay=0.05,
    )
    t = threading.Thread(target=lambda: client.work(stop_event=stop), daemon=True)
    t.start()
    return client, stop, t


def _free_dead_port():
    """A port nothing listens on: bind, read it off, close — connects to
    it fail fast with ECONNREFUSED."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _sessions_on_distinct_shards(urls):
    """Two session ids the ring homes on DIFFERENT shards of ``urls``."""
    ring = ShardRing([shard_id(a) for a in parse_broker_urls(urls)])
    by_shard = {}
    for i in range(10_000):
        sid = f"sess-{i:05d}"
        by_shard.setdefault(ring.home(sid), sid)
        if len(by_shard) == 2:
            break
    assert len(by_shard) == 2, "ring never split 10k keys across 2 shards"
    return [by_shard[s] for s in sorted(by_shard)]


class TestParseBrokerUrls:
    def test_formats(self):
        assert parse_broker_urls(["h1:7777", "tcp://h2:8888", ("h3", 9999)]) \
            == [("h1", 7777), ("h2", 8888), ("h3", 9999)]

    def test_duplicates_rejected(self):
        with pytest.raises(ValueError):
            parse_broker_urls(["h:1", "tcp://h:1"])

    def test_garbage_rejected(self):
        for bad in (["h"], ["h:notaport"], ["h:0"], [":7"], []):
            with pytest.raises(ValueError):
                parse_broker_urls(bad)

    def test_order_preserved(self):
        # Order is part of the ring identity only insofar as every party
        # must parse the SAME list; the ring itself hashes shard ids.
        assert parse_broker_urls(["b:2", "a:1"]) == [("b", 2), ("a", 1)]


class TestShardRing:
    SHARDS = ["10.0.0.1:7777", "10.0.0.2:7777", "10.0.0.3:7777"]

    def test_placement_is_deterministic_cross_process(self):
        # blake2b is keyless and unsalted: these exact placements must
        # hold in EVERY process (masters and workers agree on homes
        # without talking to each other).  Values pinned at ISSUE 18.
        ring = ShardRing(self.SHARDS)
        assert ring.home("s-alpha") == "10.0.0.1:7777"
        assert ring.home("s-beta") == "10.0.0.3:7777"
        assert ring.home("session-42") == "10.0.0.2:7777"

    def test_shard_order_does_not_matter(self):
        a = ShardRing(self.SHARDS)
        b = ShardRing(list(reversed(self.SHARDS)))
        keys = [f"s-{i:04d}" for i in range(200)]
        assert [a.home(k) for k in keys] == [b.home(k) for k in keys]

    def test_vnode_balance(self):
        ring = ShardRing(self.SHARDS)
        census = ring.census(f"s-{i:04d}" for i in range(999))
        shares = [census.get(s, 0) / 999 for s in self.SHARDS]
        # 64 vnodes/shard keeps the skew modest: no shard below 20% or
        # above 45% of a 3-shard ring (measured 29–36%).
        assert min(shares) > 0.20 and max(shares) < 0.45

    def test_minimal_movement_on_remove_and_add(self):
        ring = ShardRing(self.SHARDS)
        keys = [f"s-{i:04d}" for i in range(500)]
        before = {k: ring.home(k) for k in keys}
        victim = self.SHARDS[1]
        ring.remove(victim)
        after = {k: ring.home(k) for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        # ONLY the removed shard's keys move (the consistent-hash
        # guarantee) — and they all must, somewhere.
        assert all(before[k] == victim for k in moved)
        assert len(moved) == sum(1 for k in keys if before[k] == victim)
        # Adding it back restores the original placement exactly.
        ring.add(victim)
        assert {k: ring.home(k) for k in keys} == before

    def test_membership_errors(self):
        ring = ShardRing(self.SHARDS)
        with pytest.raises(ValueError):
            ring.add(self.SHARDS[0])
        with pytest.raises(ValueError):
            ring.remove("10.9.9.9:1")
        with pytest.raises(ValueError):
            ShardRing([])

    def test_successors_distinct_home_first(self):
        ring = ShardRing(self.SHARDS)
        succ = ring.successors("s-alpha")
        assert succ[0] == ring.home("s-alpha")
        assert sorted(succ) == sorted(self.SHARDS)


class TestShardRouter:
    def test_place_forget_and_gauges(self):
        ring = ShardRing(["a:1", "b:2"])
        router = ShardRouter(ring)
        sids = [f"s-{i}" for i in range(40)]
        for sid in sids:
            assert router.place(sid) == ring.home(sid)
        reg = get_registry()
        total = sum(
            reg.gauge("shard_sessions", shard=s).value for s in ring.shards)
        assert total == len(sids)
        for sid in sids:
            router.forget(sid)
        assert all(
            reg.gauge("shard_sessions", shard=s).value == 0
            for s in ring.shards)

    def test_set_shards_counts_moves(self):
        ring = ShardRing(["a:1", "b:2"])
        router = ShardRouter(ring)
        sids = [f"s-{i}" for i in range(60)]
        for sid in sids:
            router.place(sid)
        before = dict(router.placements())
        moved = router.set_shards(["a:1", "b:2", "c:3"])
        after = router.placements()
        assert moved == sum(1 for s in sids if before[s] != after[s])
        assert get_registry().counter("shard_rebalances_total").value == moved
        # Every moved session landed on the new shard — consistent
        # hashing moves keys only TOWARD an added member.
        assert all(after[s] == "c:3" for s in sids if before[s] != after[s])


class TestPerConnectionBackoff:
    def test_reconnect_backoff_is_per_connection(self):
        # Satellite regression (ISSUE 18): a flapping shard inflates only
        # its OWN redial delay.  One live broker + one dead port: the
        # dead conn's backoff climbs while the live conn, having
        # handshaken, stays reset at its base delay.
        broker = JobBroker(host="127.0.0.1", port=0).start()
        client = stop = None
        try:
            live = f"127.0.0.1:{broker.address[1]}"
            dead = f"127.0.0.1:{_free_dead_port()}"
            client, stop, _ = _spawn_multihome_worker(
                OneMax, [live, dead], "bk-w0", capacity=1)
            assert _wait(lambda: any(
                c.handshaken for c in client._conns), timeout=10.0)
            # Let the dead shard's manager burn a few redial cycles.
            assert _wait(lambda: next(
                c for c in client._conns if c.port != broker.address[1]
            ).backoff._next > 3 * client.reconnect_delay, timeout=10.0)
            live_conn = next(c for c in client._conns
                             if c.port == broker.address[1])
            assert live_conn.backoff._next == client.reconnect_delay
            assert live_conn.handshaken and not live_conn.dead
        finally:
            if stop is not None:
                stop.set()
            if client is not None:
                client.shutdown()
            broker.stop()

    def test_backoff_seed_is_per_endpoint(self):
        # Decorrelated jitter must not march in lockstep across shards:
        # distinct endpoint seeds give distinct delay sequences.
        from gentun_tpu.distributed.client import _ReconnectBackoff

        a = _ReconnectBackoff(0.05, 5.0, "w0:h1:1")
        b = _ReconnectBackoff(0.05, 5.0, "w0:h2:2")
        c = _ReconnectBackoff(0.05, 5.0, "w0:h1:1")
        seq_a = [a.next_delay() for _ in range(6)]
        seq_b = [b.next_delay() for _ in range(6)]
        seq_c = [c.next_delay() for _ in range(6)]
        assert seq_a == seq_c  # deterministic per seed
        assert seq_a != seq_b  # decorrelated across endpoints

    def test_multihome_rejects_multihost_and_injector(self):
        with pytest.raises(ValueError):
            GentunClient(OneMax, *DATA, broker_urls=["a:1", "b:2"],
                         multihost=True)


class TestMultihomeCreditConservation:
    def test_concurrent_sessions_two_shards_with_drain(self):
        # The satellite's core scenario: one worker homed on BOTH shards,
        # two concurrent searches whose sessions the ring homes on
        # different shards, a drain + replacement mid-run.  Proofs:
        # every job evaluated exactly once, both searches land
        # bit-identical to local evaluation, and each shard's credit
        # books balance afterwards (advertised window fully returned).
        b1 = JobBroker(host="127.0.0.1", port=0).start()
        b2 = JobBroker(host="127.0.0.1", port=0).start()
        urls = [f"127.0.0.1:{b.address[1]}" for b in (b1, b2)]
        sid_a, sid_b = _sessions_on_distinct_shards(urls)
        CountingOneMax.calls = []
        pops = errs = None
        w1 = s1 = w2 = s2 = None
        try:
            w1, s1, _ = _spawn_multihome_worker(
                CountingOneMax, urls, "mh-w1", capacity=1, prefetch_depth=2)
            pops = [
                DistributedPopulation(
                    CountingOneMax, size=6, seed=seed, maximize=True,
                    broker_urls=urls, session=sid, job_timeout=60,
                    evaluate_retries=2)
                for seed, sid in ((11, sid_a), (22, sid_b))
            ]
            errs = []

            def run_search(pop):
                try:
                    pop.evaluate()
                except BaseException as e:  # surfaced below
                    errs.append(e)

            threads = [threading.Thread(target=run_search, args=(p,))
                       for p in pops]
            for t in threads:
                t.start()
            # Drain the only worker once evaluation has started, then
            # bring up a replacement — both searches must still finish.
            assert _wait(lambda: len(CountingOneMax.calls) >= 2, timeout=30.0)
            w1.drain()
            w2, s2, _ = _spawn_multihome_worker(
                CountingOneMax, urls, "mh-w2", capacity=1, prefetch_depth=2)
            for t in threads:
                t.join(timeout=90.0)
            assert not any(t.is_alive() for t in threads)
            assert errs == []
            # Bit-identical to local evaluation (exactly-once landing of
            # the RIGHT results — a cross-session mixup would break this).
            for pop in pops:
                for ind in pop.individuals:
                    assert ind.get_fitness() == float(
                        sum(sum(g) for g in ind.genes.values()))
            # Exactly once: the drain finishes in-flight work and hands
            # unstarted jobs back, so no evaluation is repeated.
            assert len(CountingOneMax.calls) == sum(len(p.individuals)
                                                    for p in pops)
            # Credit conservation, per shard: with the fleet idle, every
            # worker's outstanding credit equals its full advertised
            # window on EACH shard it homes on, and nothing is in flight.
            for broker in (b1, b2):
                status = broker._ops_status()
                assert status["open_jobs"] == 0
                assert status["jobs_in_flight"] == 0
                for w in status["workers"]:
                    assert w["homes"] == 2
                    assert w["credit"] == w["capacity"] + w["prefetch_depth"]
        finally:
            for pop in pops or ():
                pop.close()
            for stop_evt in (s1, s2):
                if stop_evt is not None:
                    stop_evt.set()
            for client in (w1, w2):
                if client is not None:
                    client.shutdown()
            b1.stop()
            b2.stop()


class TestSessionClientRouter:
    def test_routed_submit_wait_stats(self):
        b1 = JobBroker(host="127.0.0.1", port=0).start()
        b2 = JobBroker(host="127.0.0.1", port=0).start()
        urls = [f"127.0.0.1:{b.address[1]}" for b in (b1, b2)]
        sid_a, sid_b = _sessions_on_distinct_shards(urls)
        worker = stop = None
        sc = None
        try:
            worker, stop, _ = _spawn_multihome_worker(
                OneMax, urls, "rt-w0", capacity=2)
            sc = SessionClient(broker_urls=urls)
            for sid in (sid_a, sid_b):
                sc.open_session(sid)
            payload = {
                "genes": {"S_1": [1, 1, 0, 1, 0, 1], "S_2": [1, 0, 1, 0, 1, 0]},
                "additional_parameters": {"nodes": (4, 4)},
            }
            ids = (sc.submit(sid_a, {"ja-1": payload, "ja-2": payload})
                   + sc.submit(sid_b, {"jb-1": payload}))
            results = {}
            deadline = time.monotonic() + 30.0
            while len(results) < 3 and time.monotonic() < deadline:
                r, f = sc.wait_any(ids, timeout=5.0)
                assert not f, f"unexpected failures {f}"
                results.update(r)
            assert set(results) == {"ja-1", "ja-2", "jb-1"}
            assert all(v == 7.0 for v in results.values())
            # session_stats routes to each session's home shard and sees
            # the multi-homed worker's window there.
            for sid in (sid_a, sid_b):
                stats = sc.session_stats(sid)
                assert stats["session"] == sid
                assert stats["capacity"] >= 2
            for sid in (sid_a, sid_b):
                sc.close_session(sid)
        finally:
            if sc is not None:
                sc.close()
            if stop is not None:
                stop.set()
            if worker is not None:
                worker.shutdown()
            b1.stop()
            b2.stop()

    def test_rejects_host_and_urls_together(self):
        with pytest.raises(ValueError):
            SessionClient(host="127.0.0.1", port=1, broker_urls=["a:1", "b:2"])


class TestShardedBrokerFacade:
    def test_submit_gather_across_shards(self):
        b1 = JobBroker(host="127.0.0.1", port=0).start()
        b2 = JobBroker(host="127.0.0.1", port=0).start()
        urls = [f"127.0.0.1:{b.address[1]}" for b in (b1, b2)]
        worker = stop = facade = None
        try:
            worker, stop, _ = _spawn_multihome_worker(
                OneMax, urls, "fc-w0", capacity=2)
            facade = ShardedBroker(urls)
            payload = {
                "genes": {"S_1": [1, 1, 1, 1, 1, 1], "S_2": [0, 0, 0, 0, 0, 0]},
                "additional_parameters": {"nodes": (4, 4)},
            }
            sessions = [facade.open_session() for _ in range(3)]
            ids = []
            for i, sess in enumerate(sessions):
                jid = f"fj-{i}"
                facade.submit({jid: payload}, session=sess)
                ids.append(jid)
            results = facade.gather(ids, timeout=30.0)
            assert {k: v for k, v in results.items()} == {
                jid: 6.0 for jid in ids}
            for sess in sessions:
                facade.close_session(sess)
        finally:
            if facade is not None:
                facade.stop()
            if stop is not None:
                stop.set()
            if worker is not None:
                worker.shutdown()
            b1.stop()
            b2.stop()


class TestShardedPopulationEquality:
    def test_two_shard_ga_matches_single_broker(self):
        # The headline invariant: session-affine placement means a search
        # sees ONE broker's FIFO/DRR semantics regardless of fleet shape,
        # so a 2-shard run is bit-identical to the single-broker run.
        b1 = JobBroker(host="127.0.0.1", port=0).start()
        b2 = JobBroker(host="127.0.0.1", port=0).start()
        urls = [f"127.0.0.1:{b.address[1]}" for b in (b1, b2)]
        worker = stop = pop = None
        ref = ref_worker = ref_stop = None
        try:
            worker, stop, _ = _spawn_multihome_worker(
                OneMax, urls, "eq-w0", capacity=2)
            pop = DistributedPopulation(OneMax, size=6, seed=42,
                                        maximize=True, broker_urls=urls,
                                        session="eq-session")
            GeneticAlgorithm(pop, seed=7).run(2)

            ref = DistributedPopulation(OneMax, size=6, seed=42,
                                        maximize=True, port=0)
            ref_stop = threading.Event()
            ref_worker = GentunClient(
                OneMax, *DATA, host="127.0.0.1",
                port=ref.broker_address[1], capacity=2,
                worker_id="eq-ref-w0", heartbeat_interval=0.2)
            threading.Thread(
                target=lambda: ref_worker.work(stop_event=ref_stop),
                daemon=True).start()
            GeneticAlgorithm(ref, seed=7).run(2)

            assert [i.get_fitness() for i in pop.individuals] \
                == [i.get_fitness() for i in ref.individuals]
            assert pop.get_fittest().get_fitness() \
                == ref.get_fittest().get_fitness()
        finally:
            for p in (pop, ref):
                if p is not None:
                    p.close()
            for e in (stop, ref_stop):
                if e is not None:
                    e.set()
            for c in (worker, ref_worker):
                if c is not None:
                    c.shutdown()
            b1.stop()
            b2.stop()

    def test_single_url_list_behaves_like_host_port(self):
        # A one-element broker_urls list degenerates to the classic
        # host/port client (no router, no facade) — the zero-cost
        # migration path DISTRIBUTED.md promises.
        broker = JobBroker(host="127.0.0.1", port=0).start()
        worker = stop = pop = None
        try:
            url = f"127.0.0.1:{broker.address[1]}"
            worker, stop, _ = _spawn_multihome_worker(
                OneMax, [url], "su-w0", capacity=2)
            assert worker._addrs is None  # single-URL: classic path
            pop = DistributedPopulation(OneMax, size=4, seed=3,
                                        maximize=True, broker_urls=[url])
            pop.evaluate()
            for ind in pop.individuals:
                assert ind.get_fitness() == float(
                    sum(sum(g) for g in ind.genes.values()))
        finally:
            if pop is not None:
                pop.close()
            if stop is not None:
                stop.set()
            if worker is not None:
                worker.shutdown()
            broker.stop()
