"""Multi-chip sharding tests on the virtual 8-device CPU mesh (SURVEY.md §4).

conftest pins jax to cpu with xla_force_host_platform_device_count=8, so
``jax.devices()`` is 8 virtual devices and every sharding path executes for
real (XLA partitions + collectives), just on CPU.
"""

import numpy as np
import pytest

import jax

from gentun_tpu.models.cnn import GeneticCnnModel
from gentun_tpu.parallel.mesh import (
    SIZE_BIG,
    SIZE_MICRO,
    SIZE_SMALL,
    auto_mesh,
    classify_genome_cost,
    cnn_genome_cost,
    get_mesh_override,
    host_worker_capacity,
    job_size_class,
    mesh_axis_sizes,
    mesh_factor,
    pad_population,
    parse_mesh_spec,
    pop_bucket,
    set_mesh_override,
)

FAST = dict(
    nodes=(3,),
    kernels_per_layer=(8,),
    kfold=2,
    epochs=(2,),
    learning_rate=(0.05,),
    batch_size=32,
    dense_units=32,
    compute_dtype="float32",
    seed=0,
)


@pytest.fixture(scope="module")
def separable_data():
    rng = np.random.default_rng(0)
    protos = rng.normal(size=(4, 8, 8, 1)).astype(np.float32)
    y = rng.integers(0, 4, size=192).astype(np.int32)
    x = protos[y] + 0.3 * rng.normal(size=(192, 8, 8, 1)).astype(np.float32)
    return x, y


class TestMeshConstruction:
    def test_eight_devices_available(self):
        assert jax.device_count() == 8

    def test_auto_mesh_prefers_pop_axis(self):
        mesh = auto_mesh(pop_size=16)
        assert mesh_axis_sizes(mesh) == (8, 1)

    def test_auto_mesh_spills_to_data_axis(self):
        # pop=3: largest divisor of 8 that is <= 3 is 2 → (2, 4)
        mesh = auto_mesh(pop_size=3)
        assert mesh_axis_sizes(mesh) == (2, 4)

    def test_auto_mesh_single_individual(self):
        mesh = auto_mesh(pop_size=1)
        assert mesh_axis_sizes(mesh) == (1, 8)  # pure data parallelism

    def test_explicit_axes(self):
        mesh = auto_mesh(pop_axis=4, data_axis=2)
        assert mesh_axis_sizes(mesh) == (4, 2)
        with pytest.raises(ValueError):
            auto_mesh(pop_axis=3, data_axis=2)

    def test_single_device_returns_none(self):
        assert auto_mesh(pop_size=4, devices=jax.devices()[:1]) is None

    def test_nonpositive_axis_override_is_loud(self):
        """pop_axis=0 used to fall into an `or` falsy trap and silently
        mean "unset"; any non-positive override must raise."""
        with pytest.raises(ValueError, match="pop_axis"):
            auto_mesh(pop_axis=0)
        with pytest.raises(ValueError, match="data_axis"):
            auto_mesh(data_axis=0)
        with pytest.raises(ValueError, match="pop_axis"):
            auto_mesh(pop_axis=-2, data_axis=4)
        # ... on EVERY topology, including the single device where
        # auto_mesh otherwise early-returns None before factoring.
        with pytest.raises(ValueError, match="pop_axis"):
            auto_mesh(pop_axis=0, devices=jax.devices()[:1])

    def test_mesh_factor_matches_auto_mesh(self):
        """mesh_factor is the jax-free factoring authority: the dispatch
        plane's view and the evaluator's built mesh must agree."""
        for pop_size in (None, 1, 3, 4, 16):
            mesh = auto_mesh(pop_size=pop_size)
            assert mesh_axis_sizes(mesh) == mesh_factor(8, pop_size)
        with pytest.raises(ValueError):
            mesh_factor(0)

    def test_host_worker_capacity_derivation(self):
        # power-of-two hosts land on a compile bucket that is also a
        # pop-axis multiple: zero padding, one compiled shape
        assert host_worker_capacity(1) == (2, 1, 1)
        assert host_worker_capacity(2) == (4, 2, 1)
        assert host_worker_capacity(4) == (8, 4, 1)
        assert host_worker_capacity(8) == (16, 8, 1)
        # non-power-of-two: bucket 16 isn't a multiple of pop=6 — step
        # into the exact-shape regime and round up to the pop multiple
        assert host_worker_capacity(6) == (18, 6, 1)
        assert host_worker_capacity(4, slots_per_device=4) == (16, 4, 1)

    def test_pop_bucket_is_canonical(self):
        """mesh.pop_bucket, the cnn alias, and the populations jax-free
        mirror are one policy (capacity derivation depends on it)."""
        from gentun_tpu.models.cnn import _pop_bucket
        from gentun_tpu.populations import _compile_bucket

        for n in range(1, 40):
            assert pop_bucket(n) == _pop_bucket(n) == _compile_bucket(n)

    def test_host_worker_capacity_size_class_and_override(self):
        # big/micro jobs compile 1-wide programs on a (1, n) mesh: the
        # window is exactly one job, the frame IS the job
        assert host_worker_capacity(8, size_class=SIZE_BIG) == (1, 1, 8)
        assert host_worker_capacity(8, size_class=SIZE_MICRO) == (1, 1, 8)
        # operator --mesh override replaces the heuristic factoring
        assert host_worker_capacity(8, pop_axis=4, data_axis=2) == (8, 4, 2)
        # ... and must name both axes, stay positive, and factor the host
        with pytest.raises(ValueError, match="both"):
            host_worker_capacity(8, pop_axis=4)
        with pytest.raises(ValueError, match="positive"):
            host_worker_capacity(8, pop_axis=0, data_axis=8)
        with pytest.raises(ValueError, match="factor"):
            host_worker_capacity(8, pop_axis=3, data_axis=2)
        with pytest.raises(ValueError, match="size_class"):
            host_worker_capacity(8, size_class="huge")

    def test_parse_mesh_spec(self):
        assert parse_mesh_spec("4x2") == (4, 2)
        assert parse_mesh_spec(" 8X1 ") == (8, 1)  # case/space tolerant
        for bad in ("8", "8x", "x8", "axb", "0x8", "4x-2", "2x2x2"):
            with pytest.raises(ValueError):
                parse_mesh_spec(bad)

    def test_mesh_override_precedence(self):
        """Worker ``--mesh`` reaches auto_mesh process-wide; explicit axes
        beat it; a big size class beats everything (the batch must cross
        the FULL data axis); clearing restores the heuristic."""
        set_mesh_override((2, 4))
        try:
            assert mesh_axis_sizes(auto_mesh(pop_size=16)) == (2, 4)
            assert mesh_axis_sizes(auto_mesh(pop_axis=4, data_axis=2)) == (4, 2)
            assert mesh_axis_sizes(auto_mesh(pop_size=16, size_class=SIZE_BIG)) == (1, 8)
            with pytest.raises(ValueError, match="positive"):
                set_mesh_override((0, 8))
        finally:
            set_mesh_override(None)
        assert get_mesh_override() is None
        assert mesh_axis_sizes(auto_mesh(pop_size=16)) == (8, 1)

    def test_pad_population(self):
        genomes = [{"S_1": (0, 0, 0)}, {"S_1": (1, 0, 1)}, {"S_1": (1, 1, 1)}]
        padded, n = pad_population(genomes, 4)
        assert n == 3 and len(padded) == 4
        assert padded[3] == genomes[2]
        same, n2 = pad_population(genomes, 3)
        assert n2 == 3 and same == genomes


class TestGenomeCostModel:
    """Big-genome regime (DISTRIBUTED.md): the jax-free cost model and its
    classification against a per-device memory budget."""

    COST = dict(nodes=(3,), filters=(8,), input_shape=(8, 8, 1),
                dense_units=32, n_classes=4, compute_dtype="float32")

    def test_cost_model_monotone(self):
        base = cnn_genome_cost(**self.COST)
        wider = cnn_genome_cost(**{**self.COST, "filters": (16,)})
        deeper = cnn_genome_cost(**{**self.COST, "nodes": (5,)})
        staged = cnn_genome_cost(**{**self.COST, "nodes": (3, 3),
                                    "filters": (8, 8)})
        for bigger in (wider, deeper):
            assert bigger.param_bytes > base.param_bytes
            assert bigger.act_bytes_per_example > base.act_bytes_per_example
        # an extra stage always adds live activations; its params can go
        # EITHER way (the extra pool shrinks the dense layer's input), so
        # only the activation term is asserted monotone in stage count
        assert staged.act_bytes_per_example > base.act_bytes_per_example
        # half-precision compute halves activation bytes, not param state
        # (params/momentum/grads are kept float32)
        half = cnn_genome_cost(**{**self.COST, "compute_dtype": "bfloat16"})
        assert half.act_bytes_per_example < base.act_bytes_per_example
        assert half.param_bytes == base.param_bytes

    def test_cost_model_is_jax_free(self):
        """The dispatch plane classifies jobs without touching a backend:
        mesh.py loaded standalone (the package __init__ would pull jax)
        must leave jax out of sys.modules through a full classify
        round-trip."""
        import subprocess
        import sys
        import textwrap

        from gentun_tpu.parallel import mesh as mesh_mod

        prog = textwrap.dedent(f"""
            import importlib.util, sys
            spec = importlib.util.spec_from_file_location(
                "meshonly", {mesh_mod.__file__!r})
            m = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(m)
            cost = m.cnn_genome_cost((3,), (8,), (8, 8, 1), 32, 4, "float32")
            assert m.classify_genome_cost(cost, 32, 8, 10**12) == ("small", 1)
            assert m.job_size_class({{"device_budget": 1}}) == "small"
            assert m.mesh_factor(8, 16) == (8, 1)
            leaked = [n for n in sys.modules if n == "jax" or n.startswith("jax.")]
            assert not leaked, f"jax leaked into sys.modules: {{leaked}}"
        """)
        res = subprocess.run([sys.executable, "-c", prog],
                             capture_output=True, text=True)
        assert res.returncode == 0, res.stderr

    def test_size_class_edges(self):
        cost = cnn_genome_cost(**self.COST)
        exact = cost.param_bytes + cost.act_bytes_per_example * 32
        # exactly at budget stays small (<=, not <): the wide-pop path
        assert classify_genome_cost(cost, 32, 8, exact) == (SIZE_SMALL, 1)
        assert classify_genome_cost(cost, 32, 8, exact - 1)[0] == SIZE_BIG
        # fits only with the batch sharded over the full 8-wide data axis
        big = cost.param_bytes + cost.act_bytes_per_example * 8
        assert classify_genome_cost(cost, 32, 8, big) == (SIZE_BIG, 1)
        # even the full-axis shard (4 examples/device) oversubscribes:
        # accumulate over the smallest batch divisor whose slice fits
        micro = cost.param_bytes + cost.act_bytes_per_example * 2
        assert classify_genome_cost(cost, 32, 8, micro) == (SIZE_MICRO, 2)
        # params + one example over budget: unevaluable at ANY factoring
        with pytest.raises(ValueError, match="unevaluable"):
            classify_genome_cost(cost, 32, 8, cost.param_bytes)

    def test_job_size_class_degrades_quietly(self):
        """Wire-config classification mirrors broker._parse_mesh: dispatch
        must route jobs from any master version, so feature-off, partial,
        and even unevaluable configs all degrade to small — the evaluator
        raises the loud error with full context."""
        assert job_size_class(None) == SIZE_SMALL
        assert job_size_class({}) == SIZE_SMALL
        assert job_size_class({"device_budget": None}) == SIZE_SMALL
        # no input_shape/n_classes on the wire (worker infers from data)
        assert job_size_class({"device_budget": 10**9}) == SIZE_SMALL
        tight = dict(self.COST, kernels_per_layer=(8,), batch_size=32,
                     device_budget=1)
        tight.pop("filters")
        assert job_size_class(tight) == SIZE_SMALL  # unevaluable: degrade
        cost = cnn_genome_cost(**self.COST)
        big = dict(tight,
                   device_budget=cost.param_bytes + cost.act_bytes_per_example * 8)
        assert job_size_class(big, n_devices=8) == SIZE_BIG


class TestShardedTraining:
    def test_sharded_matches_unsharded(self, separable_data):
        """The mesh changes placement, not math: same seeds → same accs."""
        x, y = separable_data
        genomes = [
            {"S_1": (0, 0, 0)},
            {"S_1": (1, 0, 1)},
            {"S_1": (1, 1, 1)},
            {"S_1": (0, 1, 1)},
        ]
        cfg = dict(FAST)
        cfg["mesh"] = None
        ref = GeneticCnnModel.cross_validate_population(x, y, genomes, **cfg)
        cfg["mesh"] = auto_mesh(pop_size=4)  # (4, 2): both axes exercised
        shd = GeneticCnnModel.cross_validate_population(x, y, genomes, **cfg)
        assert shd.shape == (4,)
        np.testing.assert_allclose(ref, shd, atol=0.06)  # CPU reduce-order jitter
        assert (shd > 0.4).all()

    def test_population_padding_roundtrip(self, separable_data):
        """pop=3 on an (8,1) mesh: padded to 8, sliced back to 3."""
        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (0, 0, 0)}, {"S_1": (1, 1, 1)}]
        cfg = dict(FAST)
        cfg["mesh"] = auto_mesh(pop_axis=8, data_axis=1)
        accs = GeneticCnnModel.cross_validate_population(x, y, genomes, **cfg)
        assert accs.shape == (3,)
        assert (accs > 0.4).all()

    def test_pad_waste_metrics(self, separable_data):
        """Mesh observability: a mesh-aligned batch wastes zero padding
        slots (``eval_pad_waste_total`` stays 0 — what a host-level
        worker's aligned dispatch schedule guarantees); a misaligned one
        counts exactly its sliced-away slots.  Axis gauges reflect the
        mesh the evaluation actually sharded over."""
        from gentun_tpu.telemetry.registry import get_registry

        x, y = separable_data
        reg = get_registry()
        reg.reset()
        cfg = dict(FAST)
        cfg["mesh"] = auto_mesh(pop_axis=8, data_axis=1)
        # aligned: all 8 possible 3-bit genomes fill the (8, 1) mesh
        genomes8 = [{"S_1": (i & 1, (i >> 1) & 1, (i >> 2) & 1)} for i in range(8)]
        GeneticCnnModel.cross_validate_population(x, y, genomes8, **cfg)
        assert reg.counter("eval_pad_waste_total").value == 0
        assert reg.gauge("mesh_pop_axis").value == 8
        assert reg.gauge("mesh_data_axis").value == 1
        # misaligned: 3 genomes pad to the mesh's 8 slots — 5 wasted
        GeneticCnnModel.cross_validate_population(x, y, genomes8[:3], **cfg)
        assert reg.counter("eval_pad_waste_total").value == 5
        reg.reset()

    def test_generous_budget_keeps_small_path_bit_identical(self, separable_data):
        """Feature on but genomes small: device_budget must only REROUTE
        big genomes — the wide-pop vmap path stays BIT identical to
        feature-off (same program, same cache keys, same fitnesses)."""
        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (0, 1, 1)}]
        ref = GeneticCnnModel.cross_validate_population(x, y, genomes, **FAST)
        on = GeneticCnnModel.cross_validate_population(
            x, y, genomes, device_budget=10**12, **FAST)
        assert np.array_equal(ref, on)

    def test_big_genome_data_sharded_path(self, separable_data):
        """A budget that forces the big class routes one-genome programs
        over the (1, 8) data-sharded mesh — bit-identical here (float32
        CPU, batch 32 divides the axis) — and a tighter budget exercises
        microbatch gradient accumulation (numerics legitimately differ:
        dropout masks follow the micro-slice shape, so only sanity-check)."""
        from gentun_tpu.telemetry.registry import get_registry

        x, y = separable_data
        genomes = [{"S_1": (1, 0, 1)}, {"S_1": (0, 1, 1)}]
        ref = GeneticCnnModel.cross_validate_population(x, y, genomes, **FAST)
        cost = cnn_genome_cost((3,), (8,), (8, 8, 1), 32, 4, "float32")
        reg = get_registry()
        reg.reset()
        big_budget = cost.param_bytes + cost.act_bytes_per_example * 8
        big = GeneticCnnModel.cross_validate_population(
            x, y, genomes, device_budget=big_budget, **FAST)
        assert np.array_equal(ref, big)
        assert reg.counter("microbatch_steps_total").value == 0
        micro_budget = cost.param_bytes + cost.act_bytes_per_example * 2
        micro = GeneticCnnModel.cross_validate_population(
            x, y, genomes, device_budget=micro_budget, **FAST)
        assert micro.shape == (2,)
        assert (micro > 0.4).all()
        assert reg.counter("microbatch_steps_total").value > 0
        reg.reset()

    def test_unevaluable_budget_is_loud(self, separable_data):
        """Evaluator-side classification never degrades: a genome whose
        parameter state + one example exceeds the budget raises before
        any compile."""
        x, y = separable_data
        cost = cnn_genome_cost((3,), (8,), (8, 8, 1), 32, 4, "float32")
        with pytest.raises(ValueError, match="unevaluable"):
            GeneticCnnModel.cross_validate_population(
                x, y, [{"S_1": (1, 0, 1)}], device_budget=cost.param_bytes,
                **FAST)

    def test_auto_mesh_is_default(self, separable_data):
        """mesh='auto' engages the 8-device mesh without explicit config."""
        x, y = separable_data
        accs = GeneticCnnModel.cross_validate_population(
            x, y, [{"S_1": (1, 0, 1)}, {"S_1": (1, 1, 0)}], **FAST
        )
        assert accs.shape == (2,)
        assert (accs > 0.4).all()


class TestLeaderWatchdog:
    """VERDICT r3 item 8: followers exit nonzero within a bounded time when
    the leader dies without sending the shutdown sentinel."""

    def _as_follower(self, monkeypatch, coordinator):
        from gentun_tpu.parallel import multihost

        monkeypatch.setattr(multihost, "is_leader", lambda: False)
        monkeypatch.setattr(multihost, "process_index", lambda: 1)
        monkeypatch.setattr(multihost, "_coordinator", coordinator)
        return multihost

    def test_exits_17_on_dead_coordinator(self, monkeypatch):
        import socket as _socket
        import time as _time

        with _socket.socket() as s:  # grab a port nobody listens on
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        mh = self._as_follower(monkeypatch, f"127.0.0.1:{dead_port}")
        exits = []
        stop = mh.start_leader_watchdog(interval=0.05, grace=2, _exit=exits.append)
        try:
            deadline = _time.monotonic() + 5.0
            while not exits and _time.monotonic() < deadline:
                _time.sleep(0.02)
            assert exits == [17]
        finally:
            stop.set()

    def test_quiet_while_coordinator_alive_and_stoppable(self, monkeypatch):
        import socket as _socket
        import time as _time

        srv = _socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(8)
        try:
            mh = self._as_follower(monkeypatch, f"127.0.0.1:{srv.getsockname()[1]}")
            exits = []
            stop = mh.start_leader_watchdog(interval=0.05, grace=2, _exit=exits.append)
            _time.sleep(0.5)
            stop.set()  # clean sentinel path
            assert exits == []
        finally:
            srv.close()

    def test_noop_on_leader(self):
        from gentun_tpu.parallel import multihost

        exits = []
        stop = multihost.start_leader_watchdog(_exit=exits.append)
        assert not stop.is_set() and exits == []  # returned without a thread
