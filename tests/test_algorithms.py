"""GA engine tests: populations, selection, evolution (SURVEY.md §4)."""

import numpy as np
import pytest

from gentun_tpu.algorithms import GeneticAlgorithm, RussianRouletteGA
from gentun_tpu.genes import genetic_cnn_genome
from gentun_tpu.individuals import Individual
from gentun_tpu.populations import GridPopulation, Population


class OneMaxIndividual(Individual):
    """Classic OneMax: fitness = number of 1 bits. A GA must solve this."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (5,))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


def make_population(size=12, seed=1, maximize=True, **params):
    return Population(
        OneMaxIndividual,
        x_train=np.zeros(1),
        y_train=np.zeros(1),
        size=size,
        seed=seed,
        maximize=maximize,
        additional_parameters=params or {"nodes": (5,)},
        mutation_rate=0.05,
    )


def test_population_random_init_deterministic():
    p1 = make_population(seed=3)
    p2 = make_population(seed=3)
    assert [i.get_genes() for i in p1] == [i.get_genes() for i in p2]
    p3 = make_population(seed=4)
    assert [i.get_genes() for i in p1] != [i.get_genes() for i in p3]


def test_get_fittest_maximize_and_minimize():
    pop = make_population()
    best = pop.get_fittest()
    assert best.get_fitness() == max(pop.get_fitnesses())
    pop_min = make_population(maximize=False)
    worst = pop_min.get_fittest()
    assert worst.get_fitness() == min(pop_min.get_fitnesses())


def test_ga_improves_onemax():
    pop = make_population(size=16, seed=0, **{"nodes": (6,)})
    ga = GeneticAlgorithm(pop, tournament_size=3, seed=0)
    initial_best = pop.get_fittest().get_fitness()
    best = ga.run(12)
    assert best.get_fitness() >= initial_best
    assert best.get_fitness() >= 12  # 15 bits total for nodes=(6,); near-optimal expected


def test_ga_run_is_reproducible():
    best1 = GeneticAlgorithm(make_population(seed=5), seed=9).run(4)
    best2 = GeneticAlgorithm(make_population(seed=5), seed=9).run(4)
    assert best1.get_genes() == best2.get_genes()
    assert best1.get_fitness() == best2.get_fitness()


def test_elitism_keeps_best_without_retraining():
    pop = make_population(size=8, seed=2)
    ga = GeneticAlgorithm(pop, elitism=True, seed=2)
    best_before = pop.get_fittest().get_fitness()
    ga.evolve_population()
    elite = ga.population[0]
    assert elite.fitness_evaluated  # cached through copy — no retrain
    assert elite.get_fitness() == best_before


def test_russian_roulette_selection_prefers_fit(monkeypatch):
    pop = make_population(size=10, seed=7)
    ga = RussianRouletteGA(pop, seed=7)
    pop.evaluate()
    weights = ga._selection_weights()
    fits = np.array(pop.get_fitnesses())
    assert weights[np.argmax(fits)] >= weights[np.argmin(fits)]
    assert np.isclose(weights.sum(), 1.0)
    # degenerate case: all-equal fitness → uniform
    for ind in pop:
        ind.set_fitness(3.0)
    assert np.allclose(ga._selection_weights(), 0.1)


def test_russian_roulette_exact_mode_is_fitness_proportional():
    """selection_floor=None = the paper's literal p ∝ f (VERDICT r4 weak #5)."""
    pop = make_population(size=6, seed=3)
    ga = RussianRouletteGA(pop, seed=3, selection_floor=None)
    pop.evaluate()
    fits = np.array(pop.get_fitnesses(), dtype=np.float64)
    assert fits.min() > 0  # OneMax accuracies; exact mode's precondition
    assert np.allclose(ga._selection_weights(), fits / fits.sum())


def test_russian_roulette_floor_scales_worst_member_chance():
    pop = make_population(size=6, seed=4)
    pop.evaluate()
    fits = np.array(pop.get_fitnesses(), dtype=np.float64)
    if fits.max() == fits.min():  # pragma: no cover - seed-dependent guard
        fits[0] -= 1.0
        for ind, f in zip(pop, fits):
            ind.set_fitness(float(f))
    worst = int(np.argmin(fits))
    w_bare = RussianRouletteGA(pop, seed=4, selection_floor=0.0)._selection_weights()
    w_def = RussianRouletteGA(pop, seed=4)._selection_weights()
    assert w_bare[worst] == 0.0  # bare range-shift truncates the worst member
    assert w_def[worst] > 0.0  # the default floor keeps it alive
    with pytest.raises(ValueError):
        RussianRouletteGA(pop, seed=4, selection_floor=-0.1)


def test_russian_roulette_improves_onemax():
    pop = make_population(size=16, seed=11, **{"nodes": (6,)})
    ga = RussianRouletteGA(pop, seed=11)
    best = ga.run(12)
    assert best.get_fitness() >= 11


def test_generation_history_records_metric():
    ga = GeneticAlgorithm(make_population(size=6, seed=1), seed=1)
    ga.run(2)
    assert len(ga.history) == 2
    rec = ga.history[0]
    assert {"generation", "best_fitness", "individuals_per_hour_per_chip"} <= set(rec)


def test_grid_population_enumerates_product():
    pop = GridPopulation(
        OneMaxIndividual,
        x_train=np.zeros(1),
        y_train=np.zeros(1),
        genes_grid={"S_1": [(0, 0, 0), (1, 1, 1)]},
        additional_parameters={"nodes": (3,)},
        seed=0,
    )
    assert len(pop) == 2
    assert sorted(p.get_fitness() for p in pop) == [0.0, 3.0]


def test_grid_population_rejects_unknown_gene():
    with pytest.raises(ValueError):
        GridPopulation(
            OneMaxIndividual,
            genes_grid={"bogus": [1]},
            additional_parameters={"nodes": (3,)},
            seed=0,
        )


def test_state_dict_restores_config_across_mismatched_population():
    """Resuming must honor the checkpoint's genome spec + rates, not the
    receiving population's construction-time config."""
    ga = GeneticAlgorithm(make_population(size=6, seed=1, **{"nodes": (6,)}), seed=1)
    ga.evolve_population()
    state = ga.state_dict()

    other = make_population(size=6, seed=9, **{"nodes": (3,)})  # wrong spec on purpose
    other.mutation_rate = 0.9
    ga2 = GeneticAlgorithm(other, seed=9)
    ga2.load_state_dict(state)
    assert ga2.population.additional_parameters == {"nodes": (6,)}
    assert ga2.population.mutation_rate == ga.population.mutation_rate
    assert [i.get_genes() for i in ga2.population] == [i.get_genes() for i in ga.population]
    assert all(i.mutation_rate == ga.population.mutation_rate for i in ga2.population)


def test_state_dict_round_trip():
    pop = make_population(size=6, seed=1)
    ga = GeneticAlgorithm(pop, seed=1)
    ga.evolve_population()
    state = ga.state_dict()

    pop2 = make_population(size=6, seed=99)
    ga2 = GeneticAlgorithm(pop2, seed=99)
    ga2.load_state_dict(state)
    assert ga2.generation == ga.generation
    assert [i.get_genes() for i in ga2.population] == [i.get_genes() for i in ga.population]
    # resumed run must continue identically
    b1 = ga.run(3)
    b2 = ga2.run(3)
    assert b1.get_genes() == b2.get_genes()


def test_old_fitness_protocol_checkpoint_drops_measurements(caplog):
    """A checkpoint written under the old slot-indexed RNG protocol must
    not feed its fitnesses into a resumed search (they are not comparable
    with content-hash measurements — utils/fitness_store.FITNESS_PROTOCOL);
    genes, RNG state, and history survive, everything re-measures."""
    import logging

    pop = make_population(size=6, seed=1)
    ga = GeneticAlgorithm(pop, seed=1)
    ga.evolve_population()
    state = ga.state_dict()
    from gentun_tpu.utils.fitness_store import FITNESS_PROTOCOL

    assert state["fitness_protocol"] == FITNESS_PROTOCOL
    assert any(i["fitness"] is not None for i in state["population"]["individuals"])
    state["fitness_protocol"] = 1  # simulate a round-4-era checkpoint

    pop2 = make_population(size=6, seed=99)
    ga2 = GeneticAlgorithm(pop2, seed=99)
    with caplog.at_level(logging.WARNING, logger="gentun_tpu"):
        ga2.load_state_dict(state)
    assert "protocol" in caplog.text
    assert ga2.population.fitness_cache == {}
    assert all(not i.fitness_evaluated for i in ga2.population)
    # the trajectory itself still resumes (genes + RNG state intact)
    assert [i.get_genes() for i in ga2.population] == [i.get_genes() for i in ga.population]
