"""Asynchronous steady-state engine (``algorithms_async.py``).

Covers the PR's acceptance gates: seeded determinism on CPU (same seed ⇒
same best genome and completion history), a capacity-2 fleet actually
sustaining ≥2 evaluations in flight (observed through the new
``jobs_in_flight`` gauge), kill/resume continuing deterministically from
the completion-boundary checkpoint, and the checkpoint schema-version
fences in both directions.
"""

import json
import threading
import time

import numpy as np
import pytest

from gentun_tpu import AsyncEvolution, GeneticAlgorithm, Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import (
    DistributedPopulation,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    GentunClient,
)
from gentun_tpu.distributed.faults import MasterKilled
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.utils import CHECKPOINT_SCHEMA, Checkpointer


class OneMax(Individual):
    """Count of set bits — a pure function of genes, so local and
    distributed evaluation agree bit-for-bit."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


class SlowOneMax(OneMax):
    """OneMax with a deliberate training delay: long enough that a sampler
    thread reliably observes the overlap of two in-flight evaluations."""

    def evaluate(self):
        time.sleep(0.3)
        return super().evaluate()


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _pop(size=8, seed=11, **kw):
    return Population(OneMax, DATA, size=size, seed=seed, maximize=True, **kw)


class TestLocalSteadyState:
    def test_budget_is_total_completions(self):
        eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1, seed=5)
        eng.run(max_evaluations=30)
        assert eng.completed == 30
        assert len(eng.history) == 30
        assert eng.best is not None

    def test_ring_stays_bounded_and_ages(self):
        pop = _pop(size=6)
        founders = list(pop)  # keep refs so id() comparison is sound
        eng = AsyncEvolution(pop, tournament_size=3, max_in_flight=1, seed=5)
        eng.run(max_evaluations=40)
        assert len(pop) == 6
        # Aging eviction: after 34 steady-state insertions the founding
        # cohort has been cycled out entirely, fit or not.
        assert not {id(f) for f in founders} & {id(ind) for ind in pop}
        assert all(ind.fitness_evaluated for ind in pop)

    def test_same_seed_same_trajectory(self):
        runs = []
        for _ in range(2):
            eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1, seed=5)
            best = eng.run(max_evaluations=40)
            runs.append((best.get_genes(), [h["fitness"] for h in eng.history]))
        assert runs[0] == runs[1]

    def test_best_survives_eviction(self):
        # self.best is a copy: even when aging evicts the champion from the
        # ring, the returned best never regresses.
        eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1, seed=5)
        best = eng.run(max_evaluations=40)
        assert best.get_fitness() == max(
            h["fitness"] for h in eng.history if h["fitness"] is not None)

    def test_cache_dedup_and_followers_still_consume_budget(self):
        # A 2-genome search space: the initial cohort contains duplicates
        # (follower path) and almost every bred child is a cache hit
        # (instant-complete path) — the budget still counts every
        # completion, so the loop terminates without ever starving.
        pop = Population(OneMax, DATA, size=4, seed=3, maximize=True,
                         additional_parameters={"nodes": (2,)})
        eng = AsyncEvolution(pop, tournament_size=2, max_in_flight=1, seed=9)
        eng.run(max_evaluations=30)
        assert eng.completed == 30
        assert any(h.get("cached") for h in eng.history)
        assert len(pop) == 4 and all(i.fitness_evaluated for i in pop)


class TestKillResume:
    def test_kill_at_boundary_resumes_deterministically(self, tmp_path):
        ref = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                             seed=5, checkpoint_every=2)
        best_ref = ref.run(max_evaluations=40)

        path = str(tmp_path / "async-ckpt.json")
        eng_a = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                               seed=5, checkpoint_every=2)
        # Fire at the 3rd checkpoint boundary — AFTER the save, so the
        # recovery contract is exactly a real crash's.
        eng_a.set_fault_injector(FaultInjector(FaultPlan([
            FaultSpec(hook="master_boundary", kind="kill_master", at=2),
        ])))
        with pytest.raises(MasterKilled):
            eng_a.run(max_evaluations=40, checkpointer=Checkpointer(path))
        assert eng_a.completed < 40

        eng_b = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                               seed=5, checkpoint_every=2)
        best_b = eng_b.run(max_evaluations=40, checkpointer=Checkpointer(path))
        assert eng_b.completed == 40
        assert best_b.get_genes() == best_ref.get_genes()
        assert [h["fitness"] for h in eng_b.history] == \
               [h["fitness"] for h in ref.history]

    def test_checkpoint_saves_in_flight_children(self, tmp_path):
        path = str(tmp_path / "inflight-ckpt.json")
        eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                             seed=5, checkpoint_every=2)
        eng.set_fault_injector(FaultInjector(FaultPlan([
            FaultSpec(hook="master_boundary", kind="kill_master", at=1),
        ])))
        with pytest.raises(MasterKilled):
            eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        # With one in-flight slot and a boundary placed after refill, the
        # checkpoint carries the bred-but-unfinished child the resumed run
        # must re-dispatch first.
        assert state["algorithm"] == "AsyncEvolution"
        assert state["dispatched"] == state["completed"] + len(state["in_flight"])


class TestCheckpointSchema:
    def test_schema_version_stamped(self, tmp_path):
        path = str(tmp_path / "ck.json")
        eng = AsyncEvolution(_pop(), max_in_flight=1, seed=5, checkpoint_every=4)
        eng.run(max_evaluations=12, checkpointer=Checkpointer(path))
        assert json.load(open(path))["schema_version"] == CHECKPOINT_SCHEMA == 4

    def test_newer_schema_refused(self, tmp_path):
        path = str(tmp_path / "ck.json")
        json.dump({"schema_version": CHECKPOINT_SCHEMA + 1}, open(path, "w"))
        with pytest.raises(ValueError, match="newer"):
            Checkpointer(path).load()

    def test_generational_refuses_async_checkpoint(self, tmp_path):
        path = str(tmp_path / "ck.json")
        eng = AsyncEvolution(_pop(), max_in_flight=1, seed=5, checkpoint_every=4)
        eng.run(max_evaluations=12, checkpointer=Checkpointer(path))
        ga = GeneticAlgorithm(_pop(), seed=1)
        with pytest.raises(ValueError, match="AsyncEvolution"):
            Checkpointer(path).resume(ga)

    def test_async_refuses_generational_checkpoint(self, tmp_path):
        path = str(tmp_path / "ck.json")
        ga = GeneticAlgorithm(_pop(), seed=1)
        ga.run(2, checkpointer=Checkpointer(path))
        eng = AsyncEvolution(_pop(), max_in_flight=1, seed=5)
        with pytest.raises(ValueError, match="not AsyncEvolution"):
            eng.run(max_evaluations=12, checkpointer=Checkpointer(path))

    def test_v1_checkpoint_still_loads(self, tmp_path):
        # Pre-versioning files (no schema_version field) are v1 and load.
        path = str(tmp_path / "ck.json")
        ga = GeneticAlgorithm(_pop(), seed=1)
        ga.run(2, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        state.pop("schema_version")
        json.dump(state, open(path, "w"))
        ga2 = GeneticAlgorithm(_pop(), seed=1)
        assert Checkpointer(path).resume(ga2)
        assert ga2.generation == ga.generation


class TestDistributedInFlight:
    def test_two_worker_fleet_sustains_capacity_in_flight(self):
        """Acceptance gate: with a capacity-2 fleet the steady-state engine
        keeps ≥2 evaluations in flight, observed via ``jobs_in_flight``."""
        spans_mod.enable()
        reg = get_registry()
        pop = DistributedPopulation(SlowOneMax, size=4, seed=7, port=0,
                                    job_timeout=60, maximize=True)
        stops, samples, sampling = [], [], threading.Event()

        def _sample():
            gauge = reg.gauge("jobs_in_flight")
            while not sampling.is_set():
                samples.append(gauge.value)
                time.sleep(0.005)

        sampler = threading.Thread(target=_sample, daemon=True)
        try:
            _, port = pop.broker_address
            for i in range(2):
                stop = threading.Event()
                client = GentunClient(
                    SlowOneMax, *DATA, host="127.0.0.1", port=port,
                    capacity=1, worker_id=f"async-w{i}",
                    heartbeat_interval=0.2, reconnect_delay=0.05,
                )
                threading.Thread(
                    target=lambda c=client, s=stop: c.work(stop_event=s),
                    daemon=True).start()
                stops.append(stop)
            eng = AsyncEvolution(pop, tournament_size=3, seed=5, job_timeout=60)
            sampler.start()
            best = eng.run(max_evaluations=12)
            assert eng.completed == 12
            # Resolved from the fleet's dispatch WINDOW: 2 × (capacity 1 +
            # default prefetch_depth = capacity), the breed-ahead target of
            # the pipelined dispatch plane.
            assert eng._cap == 4
            assert best.get_fitness() == max(
                h["fitness"] for h in eng.history if h["fitness"] is not None)
            # The fleet was actually saturated, not trickle-fed.
            assert max(samples) >= 2, f"never saw 2 in flight: max={max(samples)}"
            # Dispatch→handoff wait is being measured for every real job.
            assert reg.histogram("queue_wait_s").count > 0
            # Nothing leaked: all gauges back to zero once the run drained.
            out = pop.broker.outstanding()
            assert all(v == 0 for v in out.values()), out
        finally:
            sampling.set()
            for s in stops:
                s.set()
            pop.close()
