"""Multi-fidelity evolution: the ASHA promotion ladder, its checkpoint and
wire surfaces, and the fidelity-fingerprinted fitness store.

Covers the PR's acceptance gates not already exercised by
``scripts/fidelity_study.py``: promotion × cancel × straggler-requeue on a
real fleet (a speculatively requeued rung-k job must not double-promote;
a cancelled stale promotion must not leak ``jobs_in_flight``), the
schema-v3 checkpoint round-trip of in-flight and QUEUED promotions, the
per-rung fitness-cache/telemetry counters, and the worker-side rejection
of unknown fidelity tags with back-compat for tagless masters.
"""

import json
import threading
import time

import numpy as np
import pytest

from gentun_tpu import AsyncEvolution, Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import (
    DistributedPopulation,
    FaultInjector,
    FaultPlan,
    FaultSpec,
    GentunClient,
)
from gentun_tpu.distributed.faults import MasterKilled
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.utils import Checkpointer, fidelity_fingerprint
from gentun_tpu.utils.fitness_store import (
    STORE_VERSION,
    load_fitness_cache,
    save_fitness_cache,
)


class OneMax(Individual):
    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


class SlowOneMax(OneMax):
    def evaluate(self):
        time.sleep(0.15)
        return super().evaluate()


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))
#: Fidelity knobs chosen from FIDELITY_KNOBS so each rung fingerprints —
#: and therefore cache-keys — differently.
LADDER = [{"kfold": 2, "epochs": (1,)}, {"kfold": 5, "epochs": (4,)}]


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


def _pop(size=8, seed=11, **kw):
    return Population(OneMax, DATA, size=size, seed=seed, maximize=True, **kw)


def _engine(pop=None, ladder=LADDER, **kw):
    kw.setdefault("tournament_size", 3)
    kw.setdefault("max_in_flight", 1)
    kw.setdefault("seed", 5)
    return AsyncEvolution(pop or _pop(), fidelity_ladder=ladder, eta=3, **kw)


def _sig(eng):
    return [(h["fitness"], h.get("rung")) for h in eng.history]


class TestLadderEngine:
    def test_everything_starts_at_rung_zero_and_climbs(self):
        eng = _engine()
        best = eng.run(max_evaluations=60)
        rungs = [h["rung"] for h in eng.history]
        assert set(rungs) <= {0, 1}
        # The founding cohort and every bred child measured at rung 0 first.
        first_by = {}
        for h in eng.history:
            first_by.setdefault(h["completed"], h["rung"])
        assert rungs[0] == 0
        # Something actually promoted, and the reported best is top-rung.
        assert any(h.get("promotion") for h in eng.history)
        assert getattr(best, "_rung", None) == 1

    def test_promotion_rate_bounded_by_eta(self):
        eng = _engine()
        eng.run(max_evaluations=90)
        r0, r1 = (len(v) for v in eng._rung_completions)
        assert r1 > 0
        # The ASHA invariant the quota fix enforces: rung sizes stay
        # geometric — promotions from rung 0 never exceed completions//eta.
        assert r1 <= r0 // eng.eta

    def test_same_seed_same_trajectory(self):
        runs = []
        for _ in range(2):
            eng = _engine()
            best = eng.run(max_evaluations=60)
            runs.append((best.get_genes(), _sig(eng)))
        assert runs[0] == runs[1]

    def test_ladderless_history_shape_unchanged(self):
        # fidelity_ladder=None keeps the legacy engine bit-identical —
        # including the absence of ladder keys in history entries.
        eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1, seed=5)
        eng.run(max_evaluations=20)
        assert all("rung" not in h and "promotion" not in h for h in eng.history)

    def test_rung_overlays_key_cache_disjointly(self):
        pop = _pop(size=4, seed=3, additional_parameters={"nodes": (2,)})
        eng = _engine(pop=pop)
        eng.run(max_evaluations=40)
        # A 2-genome space at 2 rungs → at most 4 distinct cache keys, and
        # the same genes appear under BOTH rung overlays (disjoint keys).
        keys = list(pop.fitness_cache)
        params = {k[-1] for k in keys}
        assert len(params) == 2, params

    def test_statusz_rung_snapshot(self):
        eng = _engine()
        eng.run(max_evaluations=40)
        status = eng._ops_status()
        assert [r["rung"] for r in status["rungs"]] == [0, 1]
        assert status["rungs"][0]["completions"] == len(eng._rung_completions[0])
        assert status["rungs"][1]["best_fitness"] == eng.best.get_fitness()

    def test_cache_hit_and_miss_counters_per_rung(self):
        spans_mod.enable()
        pop = _pop(size=4, seed=3, additional_parameters={"nodes": (2,)})
        eng = _engine(pop=pop)
        eng.run(max_evaluations=40)
        reg = get_registry()
        hits = sum(reg.counter("fitness_cache_hits_total", rung=str(r)).value
                   for r in (0, 1))
        misses = sum(reg.counter("fitness_cache_misses_total", rung=str(r)).value
                     for r in (0, 1))
        assert hits > 0 and misses > 0
        # With 2 genomes and 2 rungs there are exactly 4 unique measurements.
        assert misses == 4
        assert reg.counter("promotions_total", rung="1").value > 0


class TestLadderCheckpoint:
    def test_schema_round_trip_with_inflight_promotion(self, tmp_path):
        ref = _engine(checkpoint_every=2)
        ref.run(max_evaluations=60)

        path = str(tmp_path / "ladder-ckpt.json")
        promotion_seen = False
        for at in range(2, 14):
            p = str(tmp_path / f"probe-{at}.json")
            eng = _engine(checkpoint_every=2)
            eng.set_fault_injector(FaultInjector(FaultPlan([
                FaultSpec(hook="master_boundary", kind="kill_master", at=at)])))
            with pytest.raises(MasterKilled):
                eng.run(max_evaluations=60, checkpointer=Checkpointer(p))
            state = json.load(open(p))
            assert state["schema_version"] == 4
            entries = state["in_flight"] + state.get("queued", [])
            if any(e.get("kind") == "promotion" for e in entries):
                promotion_seen, path = True, p
                break
        assert promotion_seen, "no kill boundary caught a promotion in flight"

        resumed = _engine(checkpoint_every=2)
        best = resumed.run(max_evaluations=60, checkpointer=Checkpointer(path))
        assert _sig(resumed) == _sig(ref)
        assert best.get_genes() == ref.best.get_genes()

    def test_laddered_state_carries_rung_fields(self, tmp_path):
        path = str(tmp_path / "ck.json")
        eng = _engine(checkpoint_every=2)
        eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        assert state["ladder"] == LADDER or state["ladder"] == [
            {**r, "epochs": list(r["epochs"])} for r in LADDER]
        assert state["eta"] == 3
        assert len(state["rung_completions"]) == 2
        assert all("rung" in m for m in state["population"]["individuals"])
        assert [b["rung"] for b in state["best_by_rung"]] == sorted(
            b["rung"] for b in state["best_by_rung"])

    def test_v2_shaped_checkpoint_resumes_into_ladder(self, tmp_path):
        # A pre-ladder (v2) checkpoint — in_flight as bare genes, no ladder
        # keys — must resume under a ladder ctor: entries become rung-0
        # children, members rung 0.
        state = path = None
        for at in range(1, 8):
            path = str(tmp_path / f"ck-{at}.json")
            legacy = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                                    seed=5, checkpoint_every=2)
            legacy.set_fault_injector(FaultInjector(FaultPlan([
                FaultSpec(hook="master_boundary", kind="kill_master", at=at)])))
            with pytest.raises(MasterKilled):
                legacy.run(max_evaluations=40, checkpointer=Checkpointer(path))
            state = json.load(open(path))
            if state["in_flight"]:
                break
        # v2 entries are bare genes dicts — no "kind"/"rung" envelope.
        assert state["in_flight"] and "kind" not in state["in_flight"][0]
        assert "ladder" not in state

        eng = _engine(checkpoint_every=2)
        eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        assert eng.completed == 40
        # The ladder applies from the resume on: later completions climb.
        assert any(h.get("rung") == 1 for h in eng.history[state["completed"]:])

    def test_ladderless_checkpoint_keeps_v2_shape(self, tmp_path):
        path = str(tmp_path / "ck.json")
        eng = AsyncEvolution(_pop(), tournament_size=3, max_in_flight=1,
                             seed=5, checkpoint_every=2)
        eng.set_fault_injector(FaultInjector(FaultPlan([
            FaultSpec(hook="master_boundary", kind="kill_master", at=1)])))
        with pytest.raises(MasterKilled):
            eng.run(max_evaluations=40, checkpointer=Checkpointer(path))
        state = json.load(open(path))
        assert "ladder" not in state and "queued" not in state
        assert state["dispatched"] == state["completed"] + len(state["in_flight"])


class TestPromotionCancel:
    def test_eviction_cancels_pending_promotion_and_run_stays_consistent(self):
        # Small ring + long budget → heavy aging eviction while promotions
        # are pending.  The engine must finish with every accounting
        # invariant intact: budget reached, no member left marked pending,
        # dispatched == completed once the queue drained.
        eng = _engine(pop=_pop(size=4), checkpoint_every=4)
        eng.run(max_evaluations=80)
        assert eng.completed == 80
        assert not any(getattr(m, "_promo_pending", False)
                       for m in eng.population)
        assert eng.dispatched == eng.completed

    def test_promotion_failure_marks_member_and_refunds_slot(self):
        class FlakyPromo(OneMax):
            def evaluate(self):
                if self.additional_parameters.get("kfold") == 5:
                    raise RuntimeError("full schedule OOM")
                return super().evaluate()

        pop = Population(FlakyPromo, DATA, size=6, seed=11, maximize=True)
        eng = AsyncEvolution(pop, tournament_size=3, max_in_flight=1, seed=5,
                             fidelity_ladder=LADDER, eta=3)
        eng.run(max_evaluations=60)
        assert eng.completed == 60
        # Every promotion attempt failed; members stay at rung 0 with their
        # proxy fitness intact and are marked no-retry.
        failed = [h for h in eng.history if h.get("failed")]
        assert failed and all(h["rung"] == 1 for h in failed)
        assert all(getattr(m, "_rung", 0) == 0 for m in eng.population)
        assert any(getattr(m, "_promo_failed_rung", None) == 1
                   for m in eng.population)
        # Refunded slots let later candidates keep trying: more attempts
        # than a single quota's worth of members.
        assert len(failed) >= 2


@pytest.mark.slow
class TestLadderFleet:
    def test_ladder_on_fleet_with_straggler_requeue_no_double_promote(self):
        """E2E: 2-worker fleet, straggler requeue armed and aggressive.  A
        requeued rung-k promotion redelivers to the other worker; result
        dedup on the broker means the engine sees ONE completion — so
        promotions stay within the eta quota and nothing leaks."""
        spans_mod.enable()
        reg = get_registry()
        pop = DistributedPopulation(
            SlowOneMax, size=6, seed=7, port=0, job_timeout=60, maximize=True,
            straggler_floor_s=0.05, straggler_k=1.1, straggler_requeue=True)
        stops = []
        try:
            _, port = pop.broker_address
            for i in range(2):
                stop = threading.Event()
                client = GentunClient(
                    SlowOneMax, *DATA, host="127.0.0.1", port=port,
                    capacity=1, worker_id=f"fid-w{i}",
                    heartbeat_interval=0.2, reconnect_delay=0.05)
                threading.Thread(
                    target=lambda c=client, s=stop: c.work(stop_event=s),
                    daemon=True).start()
                stops.append(stop)
            eng = AsyncEvolution(pop, tournament_size=3, seed=5,
                                 fidelity_ladder=LADDER, eta=3, job_timeout=60)
            eng.run(max_evaluations=24)
            assert eng.completed == 24
            r0, r1 = (len(v) for v in eng._rung_completions)
            assert r1 <= r0 // eng.eta
            # No duplicated completions: each history step is distinct.
            assert [h["completed"] for h in eng.history] == list(range(1, 25))
            # The broker went quiescent — a stale promotion cancel or a
            # requeue race would leave outstanding counts behind.
            out = pop.broker.outstanding()
            assert all(v == 0 for v in out.values()), out
            assert reg.gauge("jobs_in_flight").value == 0
        finally:
            for s in stops:
                s.set()
            pop.close()


class TestFidelityTagWire:
    def test_tagless_job_accepted(self):
        assert GentunClient._check_fidelity({"job_id": "j1", "genes": {}}) is None

    def test_matching_tag_accepted(self):
        params = {"nodes": (2,), "kfold": 2, "epochs": (1,)}
        job = {"job_id": "j1", "genes": {}, "additional_parameters": params,
               "fidelity": {"v": 1, "rung": 0,
                            "fingerprint": fidelity_fingerprint(params)}}
        assert GentunClient._check_fidelity(job) is None

    def test_unknown_tag_version_rejected(self):
        job = {"job_id": "j1", "genes": {},
               "fidelity": {"v": 2, "rung": 0, "fingerprint": "ab"}}
        reason = GentunClient._check_fidelity(job)
        assert reason is not None and "version" in reason

    def test_mislabeled_fingerprint_rejected(self):
        params = {"kfold": 2, "epochs": (1,)}
        other = fidelity_fingerprint({"kfold": 5, "epochs": (4,)})
        job = {"job_id": "j1", "genes": {}, "additional_parameters": params,
               "fidelity": {"v": 1, "rung": 0, "fingerprint": other}}
        reason = GentunClient._check_fidelity(job)
        assert reason is not None and "fingerprint" in reason

    def test_ladder_master_tags_jobs_and_fleet_accepts(self):
        # End-to-end: a laddered master stamps every dispatched job with a
        # fidelity tag; a current worker validates and evaluates normally.
        pop = DistributedPopulation(OneMax, size=4, seed=7, port=0,
                                    job_timeout=30, maximize=True)
        stop = threading.Event()
        try:
            _, port = pop.broker_address
            client = GentunClient(OneMax, *DATA, host="127.0.0.1", port=port,
                                  capacity=1, worker_id="tag-w0",
                                  heartbeat_interval=0.2, reconnect_delay=0.05)
            threading.Thread(target=lambda: client.work(stop_event=stop),
                             daemon=True).start()
            eng = AsyncEvolution(pop, tournament_size=3, seed=5,
                                 fidelity_ladder=LADDER, eta=3, job_timeout=30)
            eng.run(max_evaluations=12)
            assert eng.completed == 12
            assert any(h.get("rung") == 1 for h in eng.history)
        finally:
            stop.set()
            pop.close()

    def test_tagless_old_master_back_compat(self):
        # A ladderless master (= an old master on the wire: no fidelity
        # field is ever attached) against the CURRENT worker: everything
        # evaluates unchanged.
        pop = DistributedPopulation(OneMax, size=4, seed=7, port=0,
                                    job_timeout=30, maximize=True)
        stop = threading.Event()
        try:
            _, port = pop.broker_address
            client = GentunClient(OneMax, *DATA, host="127.0.0.1", port=port,
                                  capacity=1, worker_id="old-w0",
                                  heartbeat_interval=0.2, reconnect_delay=0.05)
            threading.Thread(target=lambda: client.work(stop_event=stop),
                             daemon=True).start()
            eng = AsyncEvolution(pop, tournament_size=3, seed=5, job_timeout=30)
            eng.run(max_evaluations=12)
            assert eng.completed == 12
        finally:
            stop.set()
            pop.close()


class TestFidelityFingerprintStore:
    def test_fingerprint_reads_only_fidelity_knobs(self):
        a = fidelity_fingerprint({"kfold": 2, "epochs": (1,), "nodes": (4, 4)})
        b = fidelity_fingerprint({"kfold": 2, "epochs": (1,), "nodes": (9, 9)})
        c = fidelity_fingerprint({"kfold": 5, "epochs": (1,), "nodes": (4, 4)})
        assert a == b != c

    def test_fingerprint_accepts_frozen_params(self):
        params = {"kfold": 2, "epochs": (1,)}
        frozen = tuple(sorted(params.items()))
        assert fidelity_fingerprint(params) == fidelity_fingerprint(frozen)

    def test_store_v3_round_trip_keeps_fidelity_keys(self, tmp_path):
        path = str(tmp_path / "store.json")
        cache = {
            ("OneMax", (("S_1", (1, 0, 1)),), (("epochs", (1,)), ("kfold", 2))): 3.0,
            ("OneMax", (("S_1", (1, 0, 1)),), (("epochs", (4,)), ("kfold", 5))): 2.5,
        }
        assert save_fitness_cache(cache, path) == 2
        data = json.load(open(path))
        assert data["version"] == STORE_VERSION == 3
        assert all(len(e) == 3 for e in data["entries"])
        assert load_fitness_cache(path) == cache

    def test_tampered_fingerprint_dropped_on_load(self, tmp_path):
        path = str(tmp_path / "store.json")
        cache = {
            ("OneMax", (("S_1", (1, 0)),), (("kfold", 2),)): 1.0,
            ("OneMax", (("S_1", (0, 1)),), (("kfold", 5),)): 2.0,
        }
        save_fitness_cache(cache, path)
        data = json.load(open(path))
        data["entries"][0][2] = "deadbeefdead"  # fidelity config renamed
        json.dump(data, open(path, "w"))
        loaded = load_fitness_cache(path)
        assert len(loaded) == 1
        assert list(loaded.values()) == [2.0]


class TestWarmStartBank:
    def _cfg(self, **kw):
        cfg = dict(nodes=(3,), kernels_per_layer=(4,), kfold=2, epochs=(1,),
                   learning_rate=(1e-2,), batch_size=8, dense_units=8,
                   seed=3, compute_dtype="float32", mesh=None)
        cfg.update(kw)
        return cfg

    def _data(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8, 8, 1)).astype(np.float32)
        y = rng.integers(0, 2, size=32).astype(np.int32)
        return x, y

    def test_warm_start_off_by_default_and_bank_untouched(self):
        from gentun_tpu.models import cnn as cnn_mod
        from gentun_tpu.models.cnn import GeneticCnnModel

        cnn_mod._WARM_BANK.clear()
        x, y = self._data()
        GeneticCnnModel.cross_validate_population(
            x, y, [{"S_1": np.array([1, 0, 1])}], **self._cfg())
        assert not cnn_mod._WARM_BANK

    def test_deposit_then_inherit_across_rungs(self):
        from gentun_tpu.models import cnn as cnn_mod
        from gentun_tpu.models.cnn import GeneticCnnModel

        cnn_mod._WARM_BANK.clear()
        x, y = self._data()
        genomes = [{"S_1": np.array([1, 0, 1])}, {"S_1": np.array([0, 1, 1])}]
        GeneticCnnModel.cross_validate_population(
            x, y, genomes, **self._cfg(warm_start=True))
        assert len(cnn_mod._WARM_BANK) == 2
        # Promotion: same genomes at a longer schedule.  The warm run must
        # differ from a cold-started identical run — the ONLY difference is
        # the inherited starting point.
        warm = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **self._cfg(warm_start=True, epochs=(2,)))
        cnn_mod._WARM_BANK.clear()
        cold = GeneticCnnModel.cross_validate_population(
            x, y, genomes, **self._cfg(warm_start=True, epochs=(2,)))
        assert not np.allclose(warm, cold)

    def test_overlay_skips_shape_mismatch(self):
        from gentun_tpu.models import cnn as cnn_mod
        from gentun_tpu.models.cnn import GeneticCnnModel

        cnn_mod._WARM_BANK.clear()
        x, y = self._data()
        genomes = [{"S_1": np.array([1, 0, 1])}]
        GeneticCnnModel.cross_validate_population(
            x, y, genomes, **self._cfg(warm_start=True))
        assert len(cnn_mod._WARM_BANK) == 1
        # Same genome under a WIDER config: every banked leaf mismatches,
        # the evaluation must still succeed from fresh inits.
        accs = GeneticCnnModel.cross_validate_population(
            x, y, genomes,
            **self._cfg(warm_start=True, kernels_per_layer=(8,), dense_units=16))
        assert accs.shape == (1,)

    def test_warm_start_does_not_change_compiled_program_key(self):
        from gentun_tpu.models.cnn import _normalize_config, _static_key

        x, y = self._data()
        on = _normalize_config(x, y, self._cfg(warm_start=True))
        off = _normalize_config(x, y, self._cfg(warm_start=False))
        assert _static_key(on, 8, 16, 16, 8) == _static_key(off, 8, 16, 16, 8)

    def test_bank_lru_bound(self):
        from gentun_tpu.models import cnn as cnn_mod

        cnn_mod._WARM_BANK.clear()
        for i in range(cnn_mod._WARM_BANK_CAP + 10):
            cnn_mod._WARM_BANK.pop((i, i), None)
            cnn_mod._WARM_BANK[(i, i)] = {"w": np.zeros(1)}
            while len(cnn_mod._WARM_BANK) > cnn_mod._WARM_BANK_CAP:
                del cnn_mod._WARM_BANK[next(iter(cnn_mod._WARM_BANK))]
        assert len(cnn_mod._WARM_BANK) == cnn_mod._WARM_BANK_CAP
        assert (0, 0) not in cnn_mod._WARM_BANK
