"""Genome spec + operator tests (SURVEY.md §4: operator determinism, bounds)."""

import json

import numpy as np
import pytest

from gentun_tpu.genes import (
    BinaryGene,
    ChoiceGene,
    FloatGene,
    GenomeSpec,
    IntGene,
    boosting_genome,
    genetic_cnn_genome,
    xgboost_genome,
)


def test_genetic_cnn_genome_shapes():
    spec = genetic_cnn_genome((3, 5))
    assert spec.names == ["S_1", "S_2"]
    assert spec["S_1"].length == 3
    assert spec["S_2"].length == 10


def test_sample_is_deterministic_under_seed():
    spec = genetic_cnn_genome((3, 5))
    a = spec.sample(np.random.default_rng(7))
    b = spec.sample(np.random.default_rng(7))
    assert a == b
    c = spec.sample(np.random.default_rng(8))
    assert a != c  # overwhelmingly likely for 13 bits


def test_sample_within_bounds(rng):
    spec = boosting_genome()
    for _ in range(50):
        value = spec.validate(spec.sample(rng))  # validate() raises if out of bounds
        assert set(value) == set(spec.names)


def test_crossover_gene_granularity(rng):
    spec = genetic_cnn_genome((3, 5))
    a = {"S_1": (0, 0, 0), "S_2": (0,) * 10}
    b = {"S_1": (1, 1, 1), "S_2": (1,) * 10}
    for _ in range(20):
        child = spec.crossover(a, b, rng)
        # whole-gene inheritance: never a mixed bit-string (SURVEY §2.3)
        assert child["S_1"] in (a["S_1"], b["S_1"])
        assert child["S_2"] in (a["S_2"], b["S_2"])


def test_crossover_rate_extremes(rng):
    spec = genetic_cnn_genome((3,))
    a, b = {"S_1": (0, 0, 0)}, {"S_1": (1, 1, 1)}
    assert spec.crossover(a, b, rng, rate=0.0) == a
    assert spec.crossover(a, b, rng, rate=1.0) == b


def test_mutation_rate_zero_is_identity(rng):
    spec = xgboost_genome()
    value = spec.sample(rng)
    assert spec.mutate(value, rng, rate=0.0) == value


def test_mutation_rate_one_flips_all_bits(rng):
    gene = BinaryGene("g", 16)
    value = gene.sample(rng)
    flipped = gene.mutate(value, rng, rate=1.0)
    assert all(x != y for x, y in zip(value, flipped))


def test_binary_mutation_rate_statistics():
    gene = BinaryGene("g", 1000)
    rng = np.random.default_rng(0)
    value = (0,) * 1000
    flips = sum(sum(gene.mutate(value, rng, rate=0.015)) for _ in range(20))
    # 20 * 1000 * 0.015 = 300 expected flips; loose 3-sigma-ish bounds
    assert 200 < flips < 420


def test_float_gene_log_scale(rng):
    gene = FloatGene("lr", 0.01, 1e-4, 1.0, log_scale=True)
    samples = [gene.sample(rng) for _ in range(200)]
    assert all(1e-4 <= s <= 1.0 for s in samples)
    # log-uniform: ~half the samples land below the geometric midpoint 1e-2
    below = sum(s < 1e-2 for s in samples)
    assert 60 < below < 140


def test_validation_rejects_bad_values():
    spec = genetic_cnn_genome((3,))
    with pytest.raises(ValueError):
        spec.validate({"S_1": (0, 1)})  # wrong length
    with pytest.raises(ValueError):
        spec.validate({"S_1": (0, 1, 2)})  # non-binary
    with pytest.raises(ValueError):
        spec.validate({})  # missing
    with pytest.raises(ValueError):
        spec.validate({"S_1": (0, 1, 0), "bogus": 1})  # unknown

    gene = IntGene("d", 5, 1, 10)
    with pytest.raises(ValueError):
        gene.validate(11)
    choice = ChoiceGene("c", "a", ("a", "b"))
    with pytest.raises(ValueError):
        choice.validate("z")


def test_genome_json_round_trip(rng):
    """Genes must survive the wire format (SURVEY.md §5 config schema)."""
    for spec in (genetic_cnn_genome((3, 5)), boosting_genome()):
        value = spec.sample(rng)
        revived = spec.validate(json.loads(json.dumps(value)))
        assert revived == value


def test_grid_enumeration():
    spec = GenomeSpec([IntGene("a", 1, 1, 3), ChoiceGene("b", "x", ("x", "y"))])
    grid = spec.grid(grid_sizes={"a": 3})
    assert len(grid) == 6
    assert {tuple(sorted(g.items())) for g in grid} == {
        (("a", i), ("b", c)) for i in (1, 2, 3) for c in ("x", "y")
    }


def test_binary_grid_values():
    gene = BinaryGene("g", 3)
    assert len(gene.grid_values()) == 8


def test_duplicate_gene_names_rejected():
    with pytest.raises(ValueError):
        GenomeSpec([IntGene("a", 1, 0, 2), IntGene("a", 1, 0, 2)])
