"""Fitness cache + dedup + group-wise batched evaluation (SURVEY.md §7 #1).

Covers the population-level levers the reference lacks: architecturally
identical genomes train once per search (canonical-key dedup), cached
fitnesses survive generations and checkpoint/resume, and divergent
``additional_parameters`` split into batched groups instead of forcing the
whole population onto the sequential path.
"""

import numpy as np

from gentun_tpu.algorithms import GeneticAlgorithm
from gentun_tpu.genes import genetic_cnn_genome
from gentun_tpu.individuals import GeneticCnnIndividual, Individual
from gentun_tpu.populations import Population


class CountingEval(Individual):
    """Sequential-path species: counts evaluate() calls."""

    calls = 0

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (3,))))

    def evaluate(self):
        type(self).calls += 1
        return float(sum(sum(g) for g in self.genes.values()))


class CountingBatchModel:
    """Batched-path fitness backend: records every cross_validate_population call."""

    calls = []  # list of (n_genomes, params_key)

    @classmethod
    def cross_validate_population(cls, x, y, genomes, **params):
        cls.calls.append((len(genomes), repr(sorted(params.items()))))
        return np.array([float(sum(sum(g) for g in gen.values())) for gen in genomes])


class BatchedCnnIndividual(GeneticCnnIndividual):
    model_cls = CountingBatchModel


def _pop(species, genomes, **params):
    data = np.zeros(1)
    inds = [
        species(x_train=data, y_train=data, genes=g, additional_parameters=dict(params))
        for g in genomes
    ]
    return Population(
        species,
        x_train=data,
        y_train=data,
        individual_list=inds,
        additional_parameters=dict(params),
    )


class TestDedupWithinGeneration:
    def test_exact_duplicates_train_once_sequential(self):
        CountingEval.calls = 0
        g = {"S_1": (1, 0, 1)}
        pop = _pop(CountingEval, [g, g, g, {"S_1": (1, 1, 1)}], nodes=(3,))
        pop.evaluate()
        assert CountingEval.calls == 2  # two distinct genomes
        assert all(ind.fitness_evaluated for ind in pop)
        assert pop[0].get_fitness() == pop[1].get_fitness() == pop[2].get_fitness()

    def test_isomorphic_architectures_share_one_training(self):
        # k=3 single-edge DAGs 1→2 and 2→3 are the same architecture up to
        # node relabeling: canonical_key collapses them (ops/dag.py).
        CountingBatchModel.calls = []
        edge_12 = {"S_1": (1, 0, 0)}
        edge_23 = {"S_1": (0, 0, 1)}
        chain = {"S_1": (1, 0, 1)}
        pop = _pop(BatchedCnnIndividual, [edge_12, edge_23, chain], nodes=(3,))
        pop.evaluate()
        assert len(CountingBatchModel.calls) == 1
        assert CountingBatchModel.calls[0][0] == 2  # one rep per canonical key
        assert all(ind.fitness_evaluated for ind in pop)

    def test_n_genomes_k_keys_trains_exactly_k(self):
        CountingBatchModel.calls = []
        genomes = [
            {"S_1": (1, 0, 0)},  # iso class A (single edge)
            {"S_1": (0, 0, 1)},  # iso class A
            {"S_1": (1, 0, 1)},  # chain 1→2→3
            {"S_1": (1, 0, 1)},  # chain again (exact dup)
            {"S_1": (1, 1, 1)},  # triangle
            {"S_1": (0, 0, 0)},  # empty
        ]
        pop = _pop(BatchedCnnIndividual, genomes, nodes=(3,))
        pop.evaluate()
        trained = sum(n for n, _ in CountingBatchModel.calls)
        assert trained == 4  # distinct keys: single-edge class, chain, triangle, empty
        assert all(ind.fitness_evaluated for ind in pop)


class TestCrossGenerationCache:
    def test_ga_never_retrains_a_seen_architecture(self):
        CountingEval.calls = 0
        pop = Population(
            CountingEval,
            x_train=np.zeros(1),
            y_train=np.zeros(1),
            size=10,
            seed=0,
            additional_parameters={"nodes": (3,)},
            mutation_rate=0.1,
        )
        ga = GeneticAlgorithm(pop, seed=0)
        ga.run(8)
        # nodes=(3,) has only 8 raw genomes; a cache-less GA would retrain
        # children every generation (~10 evals/gen).  With the cache, total
        # trainings are bounded by the number of distinct genomes.
        assert CountingEval.calls <= 8

    def test_cache_travels_through_clone_with(self):
        CountingEval.calls = 0
        g = {"S_1": (1, 0, 1)}
        pop = _pop(CountingEval, [g], nodes=(3,))
        pop.evaluate()
        assert CountingEval.calls == 1
        child = pop.spawn(genes=g)  # fresh, unevaluated individual
        nxt = pop.clone_with([child])
        nxt.evaluate()
        assert CountingEval.calls == 1  # cache hit, no retrain
        assert child.get_fitness() == pop[0].get_fitness()

    def test_cache_survives_checkpoint_roundtrip(self):
        CountingEval.calls = 0
        import json

        pop = _pop(CountingEval, [{"S_1": (1, 0, 1)}, {"S_1": (1, 1, 1)}], nodes=(3,))
        ga = GeneticAlgorithm(pop, seed=1)
        pop.evaluate()
        state = json.loads(json.dumps(ga.state_dict()))  # through-JSON, like the checkpointer

        pop2 = _pop(CountingEval, [{"S_1": (1, 0, 1)}], nodes=(3,))
        ga2 = GeneticAlgorithm(pop2, seed=1)
        ga2.load_state_dict(state)
        assert ga2.population.fitness_cache == pop.fitness_cache
        # a fresh individual with a cached genome must not retrain
        calls_before = CountingEval.calls
        probe = ga2.population.spawn(genes={"S_1": (1, 1, 1)})
        ga2.population.individuals.append(probe)
        ga2.population.evaluate()
        assert CountingEval.calls == calls_before


class TestGroupwiseBatching:
    def test_mixed_params_split_into_batched_groups(self):
        CountingBatchModel.calls = []
        data = np.zeros(1)
        fast = {"nodes": (3,), "epochs": (1,)}
        slow = {"nodes": (3,), "epochs": (2,)}
        inds = [
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)}, additional_parameters=fast),
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 1, 1)}, additional_parameters=fast),
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)}, additional_parameters=slow),
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 1, 1)}, additional_parameters=slow),
        ]
        pop = Population(
            BatchedCnnIndividual,
            x_train=data,
            y_train=data,
            individual_list=inds,
            additional_parameters=fast,
        )
        pop.evaluate()
        # Two groups, each trained in ONE batched call — not 4 sequential.
        assert len(CountingBatchModel.calls) == 2
        assert sorted(n for n, _ in CountingBatchModel.calls) == [2, 2]
        assert all(ind.fitness_evaluated for ind in pop)

    def test_same_genome_different_params_not_conflated(self):
        CountingBatchModel.calls = []
        data = np.zeros(1)
        a = {"nodes": (3,), "epochs": (1,)}
        b = {"nodes": (3,), "epochs": (2,)}
        inds = [
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)}, additional_parameters=a),
            BatchedCnnIndividual(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)}, additional_parameters=b),
        ]
        pop = Population(
            BatchedCnnIndividual, x_train=data, y_train=data, individual_list=inds, additional_parameters=a
        )
        pop.evaluate()
        # The cache key includes additional_parameters: both train.
        assert sum(n for n, _ in CountingBatchModel.calls) == 2


class TestUnhashableConfigDegrades:
    def test_unhashable_additional_parameters_still_evaluate(self):
        """Unhashable params (e.g. a bytearray) must degrade to cache-less,
        sequential evaluation — not crash Population.evaluate()."""
        CountingEval.calls = 0
        data = np.zeros(1)
        params = {"nodes": (3,), "mask": bytearray(b"x")}  # unhashable value
        inds = [
            CountingEval(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)},
                         additional_parameters=dict(params)),
            CountingEval(x_train=data, y_train=data, genes={"S_1": (1, 0, 1)},
                         additional_parameters=dict(params)),
        ]
        pop = Population(CountingEval, x_train=data, y_train=data,
                         individual_list=inds, additional_parameters=dict(params))
        pop.evaluate()
        assert all(ind.fitness_evaluated for ind in pop)
        # no cache/dedup possible: both train
        assert CountingEval.calls == 2

    def test_cache_key_memo_invalidated_by_mutation(self):
        data = np.zeros(1)
        ind = CountingEval(x_train=data, y_train=data, genes={"S_1": (0, 0, 0)},
                           additional_parameters={"nodes": (3,)})
        k1 = Population._safe_cache_key(ind)
        assert Population._safe_cache_key(ind) is ind._cache_key_memo  # memo hit
        ind.set_genes({"S_1": (1, 1, 1)})
        k2 = Population._safe_cache_key(ind)
        assert k1 != k2
