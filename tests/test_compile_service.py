"""Fleet-wide compile cache service (distributed/compile_service.py).

``utils/xla_cache.py`` already persists compiled executables on disk; the
service promotes that directory to a network cache shared by an elastic
fleet.  These tests cover the wire contract (platform-fingerprint
namespacing, version skew → 409, fingerprint mismatch → 409, byte-budget
LRU, idempotent concurrent publish), the client's read-through prefetch /
write-behind publish scans, the degradation boundary (a dead service must
cost recompiles, never exceptions, with exactly ONE degraded event), the
worker/CLI guards, and the end-to-end invariant: a search with the
service killed mid-run is bit-identical to a service-free run.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gentun_tpu import GeneticAlgorithm, Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import DistributedPopulation, GentunClient
from gentun_tpu.distributed.compile_service import (
    COMPILE_PROTOCOL,
    CompileService,
    CompileServiceClient,
    _safe_name,
    platform_components,
    platform_fingerprint,
)
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.utils import xla_cache


class _ListSink:
    def __init__(self):
        self.records = []

    def record(self, rec):
        self.records.append(rec)


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


@pytest.fixture
def service():
    svc = CompileService(port=0, max_bytes=1024 * 1024)
    svc.start()
    yield svc
    svc.stop()


FP = "aa" * 8  # a fixed platform fingerprint for wire tests


def _client(service, tmp_path, name="c", fp=FP, **kw):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    return CompileServiceClient(service.url, cache_dir=str(d),
                                fingerprint=fp, **kw)


def _write_entry(client, name, data=b"x" * 64):
    with open(os.path.join(client.cache_dir, name), "wb") as fh:
        fh.write(data)


def _post_raw(url, endpoint, body):
    req = urllib.request.Request(
        url + endpoint, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=5) as resp:
        return json.loads(resp.read().decode())


class TestPlatformFingerprint:
    def test_components_name_the_compat_facts(self):
        comps = platform_components(probe_devices=False)
        for field in ("jax", "jaxlib", "platform", "device_kind", "xla_flags"):
            assert field in comps

    def test_fingerprint_is_64_bit_hex_and_stable(self):
        fp = platform_fingerprint(probe_devices=False)
        assert len(fp) == 16
        int(fp, 16)
        assert fp == platform_fingerprint(probe_devices=False)

    def test_xla_flags_change_the_fingerprint(self, monkeypatch):
        # An env knob that changes codegen must change the namespace: a
        # binary built under different XLA flags is a different binary.
        base = platform_fingerprint(probe_devices=False)
        monkeypatch.setenv("XLA_FLAGS", "--xla_something_else=1")
        assert platform_fingerprint(probe_devices=False) != base

    def test_safe_name_charset_is_the_path_guard(self):
        assert _safe_name("a1b2_c3.d-e")
        assert not _safe_name("../etc/passwd")
        assert not _safe_name("a/b")
        assert not _safe_name(".hidden")
        assert not _safe_name("")
        assert not _safe_name(42)


class TestServiceWire:
    def test_publish_prefetch_roundtrip(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        b = _client(service, tmp_path, "b")
        _write_entry(a, "entry_one", b"artifact-bytes")
        assert a.scan_publish() == 1
        assert a.flush(5.0)
        assert b.prefetch() == 1
        with open(os.path.join(b.cache_dir, "entry_one"), "rb") as fh:
            assert fh.read() == b"artifact-bytes"
        a.close(), b.close()

    def test_scan_is_noop_when_dir_unchanged(self, service, tmp_path):
        c = _client(service, tmp_path)
        _write_entry(c, "entry_one")
        assert c.scan_publish() == 1
        # Steady state: one os.stat, nothing queued, no HTTP.
        assert c.scan_publish() == 0
        assert c.scan_publish() == 0
        c.close()

    def test_prefetch_skips_entries_already_local(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        _write_entry(a, "entry_one")
        a.scan_publish()
        assert a.flush(5.0)
        # A's own entry is local already — nothing to fetch.
        assert a.prefetch() == 0
        a.close()

    def test_idempotent_republish_keeps_byte_accounting(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        b = _client(service, tmp_path, "b")
        data = b"z" * 100
        _write_entry(a, "entry_one", data)
        _write_entry(b, "entry_one", data)  # both workers compiled the shape
        a.scan_publish(), b.scan_publish()
        assert a.flush(5.0) and b.flush(5.0)
        st = service.stats()
        assert st["entries"] == 1  # content-addressed: one blob, not two
        assert st["bytes"] == len(data)
        a.close(), b.close()

    def test_concurrent_publish_of_same_blob_is_idempotent(self, service, tmp_path):
        # N threads racing the same artifact through the threading server:
        # the store must end with exactly one entry and exact byte totals.
        data = b"q" * 256
        clients = [_client(service, tmp_path, f"w{i}") for i in range(6)]
        for c in clients:
            _write_entry(c, "entry_shared", data)
        threads = [threading.Thread(target=c.scan_publish) for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for c in clients:
            assert c.flush(5.0)
        st = service.stats()
        assert st["entries"] == 1
        assert st["bytes"] == len(data)
        assert st["puts"] == 6  # all six re-publishes accepted, no error
        for c in clients:
            c.close()

    def test_byte_budget_lru_eviction(self, tmp_path):
        svc = CompileService(port=0, max_bytes=250).start()
        try:
            c = _client(svc, tmp_path)
            for i, name in enumerate(["entry_a", "entry_b", "entry_c"]):
                _write_entry(c, name, bytes([65 + i]) * 100)
                c.scan_publish()
                assert c.flush(5.0)
            st = svc.stats()
            assert st["entries"] == 2  # 300 bytes > 250: coldest evicted
            assert st["evictions"] == 1
            assert "entry_a" not in svc.list_names(FP)
            assert "entry_c" in svc.list_names(FP)
            c.close()
        finally:
            svc.stop()

    def test_fetch_refreshes_lru_position(self, tmp_path):
        svc = CompileService(port=0, max_bytes=250).start()
        try:
            a = _client(svc, tmp_path, "a")
            for name in ("entry_a", "entry_b"):
                _write_entry(a, name, b"x" * 100)
            a.scan_publish()
            assert a.flush(5.0)
            # Touch entry_a via a fetch, then push a third blob: entry_b
            # (now coldest) evicts, not entry_a.
            assert svc.fetch(FP, ["entry_a"])
            b = _client(svc, tmp_path, "b")
            _write_entry(b, "entry_c", b"x" * 100)
            b.scan_publish()
            assert b.flush(5.0)
            names = svc.list_names(FP)
            assert "entry_a" in names and "entry_b" not in names
            a.close(), b.close()
        finally:
            svc.stop()

    def test_statusz_serves_cache_block(self, service, tmp_path):
        c = _client(service, tmp_path)
        _write_entry(c, "entry_one")
        c.scan_publish()
        assert c.flush(5.0)
        with urllib.request.urlopen(service.url + "/statusz", timeout=5) as r:
            st = json.loads(r.read().decode())
        assert st["entries"] == 1 and st["puts"] == 1
        assert st["protocol"] == COMPILE_PROTOCOL
        assert st["fingerprints"] == 1
        c.close()

    def test_unsafe_names_never_stored(self, service):
        out = _post_raw(service.url, "/v1/publish", {
            "v": 1, "protocol": COMPILE_PROTOCOL, "fingerprint": FP,
            "entries": [["../escape", "eHg="], ["ok_name", "not base64!!"]]})
        assert out["stored"] == 0
        assert service.stats()["entries"] == 0


class TestConflicts:
    def test_protocol_skew_is_409(self, service):
        body = {"v": 1, "protocol": COMPILE_PROTOCOL + 1, "fingerprint": FP,
                "names": ["entry_one"]}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_raw(service.url, "/v1/fetch", body)
        assert ei.value.code == 409
        refusal = json.loads(ei.value.read().decode())
        assert refusal["protocol"] == COMPILE_PROTOCOL
        assert refusal["client_protocol"] == COMPILE_PROTOCOL + 1

    def test_fingerprint_mismatch_fetch_is_409(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        _write_entry(a, "entry_one")
        a.scan_publish()
        assert a.flush(5.0)
        # A different platform asking for the same name: refused with both
        # sides' fingerprints, never served an incompatible binary.
        body = {"v": 1, "protocol": COMPILE_PROTOCOL, "fingerprint": "bb" * 8,
                "names": ["entry_one"]}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_raw(service.url, "/v1/fetch", body)
        assert ei.value.code == 409
        refusal = json.loads(ei.value.read().decode())
        assert refusal["error"] == "platform fingerprint mismatch"
        assert refusal["stored_fingerprint"] == FP
        assert refusal["client_fingerprint"] == "bb" * 8
        assert service.stats()["conflicts"] == 1
        a.close()

    def test_fingerprint_mismatch_publish_is_409(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        _write_entry(a, "entry_one")
        a.scan_publish()
        assert a.flush(5.0)
        body = {"v": 1, "protocol": COMPILE_PROTOCOL, "fingerprint": "bb" * 8,
                "entries": [["entry_one", "eHg="]]}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_raw(service.url, "/v1/publish", body)
        assert ei.value.code == 409
        a.close()

    def test_mismatched_client_degrades_not_raises(self, service, tmp_path):
        a = _client(service, tmp_path, "a")
        _write_entry(a, "entry_one")
        a.scan_publish()
        assert a.flush(5.0)
        skewed = _client(service, tmp_path, "skewed", fp="bb" * 8,
                         timeout=2.0, cooldown=30.0)
        _write_entry(skewed, "entry_one")
        skewed.scan_publish()  # must not raise
        assert not skewed.flush(2.0)  # 409 → degraded, entries stay local
        assert skewed.degraded
        a.close(), skewed.close(flush_timeout=0.1)

    def test_disjoint_fingerprints_coexist(self, service, tmp_path):
        a = _client(service, tmp_path, "a", fp="aa" * 8)
        b = _client(service, tmp_path, "b", fp="bb" * 8)
        _write_entry(a, "entry_a")
        _write_entry(b, "entry_b")
        a.scan_publish(), b.scan_publish()
        assert a.flush(5.0) and b.flush(5.0)
        assert service.list_names("aa" * 8) == ["entry_a"]
        assert service.list_names("bb" * 8) == ["entry_b"]
        assert service.stats()["fingerprints"] == 2
        a.close(), b.close()


class TestDegradation:
    def test_dead_service_costs_recompiles_never_exceptions(self, tmp_path):
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        d = tmp_path / "cache"
        d.mkdir()
        c = CompileServiceClient("http://127.0.0.1:1", cache_dir=str(d),
                                 fingerprint=FP, timeout=0.2, cooldown=30.0)
        assert c.prefetch() == 0  # miss, not exception
        _write_entry(c, "entry_one")
        assert c.scan_publish() == 1  # queues locally
        assert not c.flush(1.0)  # can't drain to a dead service
        assert c.degraded
        evs = [r for r in sink.records
               if r.get("type") == "event"
               and r["name"] == "compile_service_degraded"]
        assert len(evs) == 1  # ONE event per transition
        assert evs[0]["data"]["url"] == "http://127.0.0.1:1"
        assert get_registry().counter("compile_service_degraded_total").value == 1
        c.close(flush_timeout=0.1)

    def test_cooldown_prevents_per_batch_timeouts(self, tmp_path):
        d = tmp_path / "cache"
        d.mkdir()
        c = CompileServiceClient("http://127.0.0.1:1", cache_dir=str(d),
                                 fingerprint=FP, timeout=0.2, cooldown=60.0)
        c.prefetch()  # pays the one connect failure
        t0 = time.monotonic()
        for _ in range(50):
            c.prefetch()  # inside the cooldown: no socket touch
        assert time.monotonic() - t0 < 0.5
        c.close(flush_timeout=0.1)

    def test_recovery_after_cooldown(self, tmp_path):
        svc = CompileService(port=0).start()
        host, port = svc.address
        a = _client(svc, tmp_path, "a")
        _write_entry(a, "entry_one")
        svc.stop()
        a.cooldown = 0.1
        a.scan_publish()
        assert not a.flush(0.5)
        assert a.degraded
        svc2 = CompileService(host=host, port=port).start()
        try:
            time.sleep(0.15)  # cooldown expires; flusher retries and heals
            assert a.flush(5.0)
            assert not a.degraded
            assert svc2.stats()["entries"] == 1
        finally:
            svc2.stop()
        a.close(flush_timeout=0.1)


class TestPublishHooks:
    def test_hook_registry_drives_publish(self, service, tmp_path):
        c = _client(service, tmp_path)
        xla_cache.register_publish_hook(c.publish_hook)
        try:
            _write_entry(c, "entry_one")
            xla_cache.run_publish_hooks()  # what _prepare_population_setup calls
            assert c.flush(5.0)
            assert service.stats()["entries"] == 1
        finally:
            c.close()  # close() unregisters
        assert c.publish_hook not in xla_cache._publish_hooks

    def test_failing_hook_never_raises(self):
        def _boom():
            raise RuntimeError("hook boom")

        xla_cache.register_publish_hook(_boom)
        try:
            xla_cache.run_publish_hooks()  # must not raise
        finally:
            xla_cache.unregister_publish_hook(_boom)


class OneMax(Individual):
    """Cheap deterministic fitness (count of set bits): distributed and
    local runs are comparable bit-for-bit, and no jax backend is touched."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


class TestClientGuards:
    def test_gentun_client_rejects_malformed_url(self):
        with pytest.raises(ValueError, match="scheme"):
            GentunClient(OneMax, *DATA, compile_cache_url="not-a-url")

    def test_gentun_client_refuses_multihost(self):
        with pytest.raises(ValueError, match="multihost"):
            GentunClient(OneMax, *DATA, multihost=True,
                         compile_cache_url="http://127.0.0.1:9737")

    def test_worker_cli_malformed_url_is_systemexit(self):
        from gentun_tpu.distributed.worker import main as worker_main

        with pytest.raises(SystemExit, match="--compile-cache-url"):
            worker_main(["--dataset", "uci-wine",
                         "--compile-cache-url", "definitely-not-a-url"])

    def test_worker_cli_refuses_multihost(self):
        from gentun_tpu.distributed.worker import main as worker_main

        with pytest.raises(SystemExit, match="--compile-cache-url"):
            worker_main(["--dataset", "uci-wine",
                         "--compile-cache-url", "http://127.0.0.1:9737",
                         "--coordinator", "127.0.0.1:8476"])


class TestEndToEnd:
    def test_service_killed_mid_search_is_bit_identical(self, tmp_path, monkeypatch):
        """The acceptance invariant: kill the compile service mid-search →
        the search completes bit-identical to a service-free run, with
        exactly ONE ``compile_service_degraded`` event."""
        generations, pop_size, pop_seed, ga_seed = 4, 8, 42, 7

        def _snapshot(ga):
            return {
                "history": [r["best_fitness"] for r in ga.history],
                "final": [
                    {"genes": {k: list(v) for k, v in ind.get_genes().items()},
                     "fitness": ind.get_fitness()}
                    for ind in ga.population
                ],
            }

        # Service-free reference (single-process, telemetry-free).
        ref = GeneticAlgorithm(
            Population(OneMax, *DATA, size=pop_size, seed=pop_seed),
            seed=ga_seed)
        ref.run(generations)

        # The worker's compile client resolves its cache dir from the env.
        cache_dir = tmp_path / "xla"
        monkeypatch.setenv("GENTUN_TPU_CACHE_DIR", str(cache_dir))
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)

        svc = CompileService(port=0).start()
        # Pre-seed one artifact under the worker's fingerprint (OneMax
        # never probes devices) so the join-time prefetch has work to do.
        wfp = platform_fingerprint(probe_devices=False)
        svc.publish(wfp, [("entry_warm", b"warm-artifact")])

        stop = threading.Event()
        try:
            with DistributedPopulation(
                    OneMax, size=pop_size, seed=pop_seed, port=0,
                    job_timeout=60.0) as pop:
                _, port = pop.broker_address
                worker = GentunClient(
                    OneMax, *DATA, port=port, capacity=4,
                    heartbeat_interval=0.2, reconnect_delay=0.05,
                    compile_cache_url=svc.url)
                t = threading.Thread(
                    target=lambda: worker.work(stop_event=stop), daemon=True)
                t.start()
                ga = GeneticAlgorithm(pop, seed=ga_seed)

                def _kill_then_dirty():
                    # Pull the plug mid-search, then write a fresh "compile
                    # artifact" so the next batch's publish scan has to talk
                    # to the dead service → the degraded path fires.
                    while not ga.history:
                        time.sleep(0.005)
                    svc.stop()
                    with open(cache_dir / "entry_fresh", "wb") as fh:
                        fh.write(b"freshly-compiled")

                killer = threading.Thread(target=_kill_then_dirty, daemon=True)
                killer.start()
                ga.run(generations)
                killer.join(timeout=10)
                stats = worker._compile_client.stats()
        finally:
            stop.set()
            try:
                svc.stop()
            except Exception:
                pass

        assert _snapshot(ga) == _snapshot(ref), (
            "compile-service kill perturbed the search")
        assert len(ga.history) == generations
        # The join-time prefetch pulled the pre-seeded artifact down.
        assert (cache_dir / "entry_warm").read_bytes() == b"warm-artifact"
        assert stats["fetched"] == 1
        # ONE degraded event for the kill.
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5.0:
            evs = [r for r in sink.records
                   if r.get("type") == "event"
                   and r["name"] == "compile_service_degraded"]
            if evs:
                break
            time.sleep(0.02)  # flusher may still be timing out on the POST
        assert len(evs) == 1, f"expected ONE degraded event, got {len(evs)}"
