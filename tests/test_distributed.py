"""Distributed-layer tests: broker semantics + fault injection.

SURVEY.md §4 "Consequence for the rebuild": distributed tests without a
cluster — in-process broker, worker threads/processes, fault injection
(worker death mid-job ⇒ redelivery), all on localhost TCP.
"""

import multiprocessing
import os
import signal
import socket
import threading
import time

import numpy as np
import pytest

from gentun_tpu import GeneticAlgorithm, Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import (
    AuthError,
    DistributedGridPopulation,
    DistributedPopulation,
    GentunClient,
    JobBroker,
    JobFailed,
)
from gentun_tpu.distributed.protocol import decode, encode


class OneMax(Individual):
    """Cheap deterministic fitness: count of set bits."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


class SlowOneMax(OneMax):
    def evaluate(self):
        time.sleep(float(self.additional_parameters.get("delay", 0.5)))
        return super().evaluate()


class AlwaysFails(OneMax):
    def evaluate(self):
        raise RuntimeError("boom")


class FlakyOneMax(OneMax):
    """Fails on the all-zero genome for its first two attempts, then heals
    (worker threads share this process's memory, so the counter is visible)."""

    attempts = 0

    def evaluate(self):
        if sum(sum(g) for g in self.genes.values()) == 0:
            FlakyOneMax.attempts += 1
            if FlakyOneMax.attempts <= 2:
                raise RuntimeError("flaky boom")
        return super().evaluate()


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


def _run_worker(species, port, password=None, capacity=1, max_jobs=None, delay_params=None):
    client = GentunClient(
        species,
        *DATA,
        host="127.0.0.1",
        port=port,
        password=password,
        capacity=capacity,
        heartbeat_interval=0.2,
        reconnect_delay=0.1,
    )
    return client.work(max_jobs=max_jobs)


def _start_worker_thread(species, port, **kw):
    stop = threading.Event()
    t = threading.Thread(
        target=lambda: GentunClient(
            species, *DATA, host="127.0.0.1", port=port,
            password=kw.get("password"), capacity=kw.get("capacity", 1),
            heartbeat_interval=0.2, reconnect_delay=0.1,
            fitness_store=kw.get("fitness_store"),
        ).work(stop_event=stop),
        daemon=True,
    )
    t.start()
    return stop, t


def _worker_process_main(port):
    """Forked worker that takes a slow job — the kill-target."""
    _run_worker(SlowOneMax, port)


@pytest.fixture
def pop4():
    p = DistributedPopulation(OneMax, size=4, seed=0, port=0)
    yield p
    p.close()


class TestBrokerBasics:
    def test_evaluate_with_one_worker(self, pop4):
        _, port = pop4.broker_address
        stop, _ = _start_worker_thread(OneMax, port)
        try:
            pop4.evaluate()
            fits = [ind.get_fitness() for ind in pop4]
            expected = [float(sum(sum(g) for g in ind.genes.values())) for ind in pop4]
            assert fits == expected
        finally:
            stop.set()

    def test_competing_consumers_split_work(self):
        with DistributedPopulation(OneMax, size=12, seed=1, port=0) as pop:
            _, port = pop.broker_address
            stops = [_start_worker_thread(OneMax, port)[0] for _ in range(3)]
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                for s in stops:
                    s.set()

    def test_capacity_batching(self):
        """capacity>1 workers receive job batches and answer them all."""
        with DistributedPopulation(OneMax, size=8, seed=2, port=0) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port, capacity=4)
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                stop.set()

    def test_capacity_batch_arrives_as_one_frame(self):
        """Credit-based prefetch: a capacity-8 worker's whole batch arrives in
        a single `jobs` frame — no drain window, latency-independent."""
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            payloads = {
                f"j{i}": {"genes": {"S_1": [i]}, "additional_parameters": {}}
                for i in range(8)
            }
            broker.submit(payloads)
            sock = socket.create_connection(("127.0.0.1", port))
            rfile = sock.makefile("rb")
            sock.sendall(encode({"type": "hello", "worker_id": "probe", "capacity": 8}))
            assert decode(rfile.readline())["type"] == "welcome"
            sock.sendall(encode({"type": "ready", "credit": 8}))
            msg = decode(rfile.readline())
            assert msg["type"] == "jobs"
            assert len(msg["jobs"]) == 8  # ALL co-delivered jobs, one frame
            sock.close()
        finally:
            broker.stop()

    def test_bad_token_rejected(self):
        with DistributedPopulation(OneMax, size=2, seed=0, port=0, password="s3cret") as pop:
            _, port = pop.broker_address
            # wrong password: worker is rejected, jobs stay pending
            client = GentunClient(OneMax, *DATA, port=port, password="wrong", reconnect_delay=0.05)
            with pytest.raises((ConnectionError, OSError)):
                client._connect()
            # right password: work completes
            stop, _ = _start_worker_thread(OneMax, port, password="s3cret")
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                stop.set()

    def test_auth_failure_is_terminal(self):
        """A wrong token must make work() raise promptly, not spin in the
        reconnect loop forever (VERDICT r2 weak #2)."""
        with DistributedPopulation(OneMax, size=2, seed=0, port=0, password="s3cret") as pop:
            _, port = pop.broker_address
            client = GentunClient(OneMax, *DATA, port=port, password="wrong", reconnect_delay=0.05)
            t0 = time.monotonic()
            with pytest.raises(AuthError):
                client.work()
            assert time.monotonic() - t0 < 5.0  # terminal, not a retry loop

    def test_gather_timeout(self):
        with DistributedPopulation(OneMax, size=2, seed=0, port=0, job_timeout=0.3) as pop:
            with pytest.raises(TimeoutError):
                pop.evaluate()  # no workers connected
            # timeout prunes + cancels: no state left to leak, and a retry
            # starts clean (late results would be dropped as stale)
            time.sleep(0.2)  # let the loop thread process the cancel
            assert pop.broker._results == {}
            assert pop.broker._failures == {}
            assert pop.broker._payloads == {}
            assert pop.broker._sched.depth() == 0  # cancelled ids drained too

    def test_non_ascii_password_accepted(self):
        """hmac token compare must handle non-ASCII secrets (UTF-8 bytes)."""
        with DistributedPopulation(
            OneMax, size=2, seed=0, port=0, password="sécret", job_timeout=10.0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port, password="sécret")
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                stop.set()

    def test_fail_fast_when_failure_and_no_workers(self):
        """A recorded permanent failure + zero connected workers must not
        hang a timeout-less gather: the barrier fails fast and cancels."""
        with DistributedPopulation(
            AlwaysFails, size=3, seed=5, port=0, max_attempts=1, job_timeout=None,
            heartbeat_timeout=1.0,  # fail-fast waits a full heartbeat window
        ) as pop:
            _, port = pop.broker_address

            def fail_one_then_vanish():
                sock = socket.create_connection(("127.0.0.1", port))
                rfile = sock.makefile("rb")
                sock.sendall(encode({"type": "hello", "worker_id": "quitter", "capacity": 1}))
                assert decode(rfile.readline())["type"] == "welcome"
                sock.sendall(encode({"type": "ready", "credit": 1}))
                msg = decode(rfile.readline())
                job_id = msg["jobs"][0]["job_id"]
                sock.sendall(encode({"type": "fail", "job_id": job_id, "reason": "boom"}))
                time.sleep(0.2)  # let the broker record the failure
                sock.close()  # vanish with 2 jobs still pending, no workers left

            t = threading.Thread(target=fail_one_then_vanish, daemon=True)
            t.start()
            done = {}

            def master():
                try:
                    pop.evaluate()
                except JobFailed as e:
                    done["failures"] = len(e.failures)

            mt = threading.Thread(target=master, daemon=True)
            mt.start()
            mt.join(timeout=20.0)
            assert not mt.is_alive(), "gather hung despite permanent failure + no workers"
            assert done.get("failures", 0) >= 1

    def test_duplicate_result_first_wins(self):
        broker = JobBroker(port=0).start()
        try:
            broker.submit({"j1": {"genes": {}, "additional_parameters": {}}})
            time.sleep(0.2)  # let the loop thread enqueue

            class W:  # stand-in worker for the dedup bookkeeping
                def __init__(self):
                    self.in_flight = {"j1"}

            broker._on_result(W(), {"type": "result", "job_id": "j1", "fitness": 1.0})
            # redelivery race: a second worker reports later — dropped
            broker._on_result(W(), {"type": "result", "job_id": "j1", "fitness": 9.0})
            assert broker.gather(["j1"], timeout=1.0) == {"j1": 1.0}
            # gather pruned master-side state (SURVEY.md long-search hygiene)
            assert broker._results == {} and broker._payloads == {}
        finally:
            broker.stop()


class TestFaultInjection:
    def test_worker_killed_mid_job_redelivers(self):
        """SIGKILL a worker holding a job; the survivor finishes everything."""
        with DistributedPopulation(
            SlowOneMax, size=3, seed=3, port=0,
            additional_parameters={"delay": 0.6},
        ) as pop:
            _, port = pop.broker_address
            ctx = multiprocessing.get_context("fork")
            victim = ctx.Process(target=_worker_process_main, args=(port,), daemon=True)
            victim.start()

            done = {}

            def master():
                pop.evaluate()
                done["ok"] = all(ind.fitness_evaluated for ind in pop)

            mt = threading.Thread(target=master, daemon=True)
            mt.start()
            time.sleep(1.0)  # victim has taken a job and is mid-evaluation
            os.kill(victim.pid, signal.SIGKILL)
            victim.join(timeout=5.0)

            stop, _ = _start_worker_thread(SlowOneMax, port)
            try:
                mt.join(timeout=30.0)
                assert done.get("ok"), "master barrier did not complete after redelivery"
            finally:
                stop.set()

    def test_hung_worker_heartbeat_reaper_redelivers(self):
        """A worker that takes a job and goes silent (no pings) is reaped."""
        with DistributedPopulation(
            OneMax, size=2, seed=4, port=0, heartbeat_timeout=1.0,
        ) as pop:
            _, port = pop.broker_address
            # Hand-rolled zombie: speaks hello/ready, takes jobs, never pings.
            sock = socket.create_connection(("127.0.0.1", port))
            rfile = sock.makefile("rb")
            sock.sendall(encode({"type": "hello", "worker_id": "zombie", "capacity": 2}))
            assert decode(rfile.readline())["type"] == "welcome"
            sock.sendall(encode({"type": "ready", "credit": 2}))

            done = {}

            def master():
                pop.evaluate()
                done["ok"] = all(ind.fitness_evaluated for ind in pop)

            mt = threading.Thread(target=master, daemon=True)
            mt.start()
            # zombie receives the jobs, holds them silently
            time.sleep(0.5)
            stop, _ = _start_worker_thread(OneMax, port)
            try:
                mt.join(timeout=15.0)
                assert done.get("ok"), "reaper did not requeue the zombie's jobs"
            finally:
                stop.set()
                sock.close()

    def test_failing_job_exhausts_attempts(self):
        with DistributedPopulation(
            AlwaysFails, size=1, seed=5, port=0, max_attempts=2, job_timeout=20.0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(AlwaysFails, port)
            try:
                with pytest.raises(JobFailed):
                    pop.evaluate()
                # gather pruned ALL failure state on raise (no leak across
                # generations, and a resubmit starts with fresh attempts)
                assert pop.broker._failures == {}
                assert pop.broker._fail_counts == {}
            finally:
                stop.set()

    def test_job_failed_keeps_partial_results_and_retry_reships_only_failures(self):
        """Post-JobFailed semantics: finished work is applied, evaluate()
        again reships only the failed individuals (with fresh attempts)."""
        FlakyOneMax.attempts = 0
        bad = {"S_1": (0,) * 6, "S_2": (0,) * 6}  # the genome FlakyOneMax chokes on
        good1 = {"S_1": (1,) * 6, "S_2": (1,) * 6}
        good2 = {"S_1": (1, 0, 1, 0, 1, 0), "S_2": (0, 1, 0, 1, 0, 1)}
        inds = [
            FlakyOneMax(genes=g, additional_parameters={"nodes": (4, 4)})
            for g in (good1, bad, good2)
        ]
        with DistributedPopulation(
            FlakyOneMax,
            individual_list=inds,
            additional_parameters={"nodes": (4, 4)},
            port=0,
            max_attempts=2,
            job_timeout=30.0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(FlakyOneMax, port)
            try:
                with pytest.raises(JobFailed) as ei:
                    pop.evaluate()
                # the two healthy individuals kept their results
                assert pop[0].fitness_evaluated and pop[2].fitness_evaluated
                assert not pop[1].fitness_evaluated
                assert len(ei.value.failures) == 1
                assert len(ei.value.partial) == 2
                # retry: only the failed individual is reshipped; FlakyOneMax
                # has burnt its 2 failures and now succeeds
                shipped = pop.evaluate()
                assert shipped == 1
                assert pop[1].get_fitness() == 0.0
            finally:
                stop.set()


class GlitchyOneMax(OneMax):
    """Every distinct genome transiently fails its first two in-process
    evaluation attempts, then heals.  With broker ``max_attempts=2`` that
    deterministically exhausts delivery attempts for fresh work — a real
    mid-search ``JobFailed`` — while the next evaluate() pass (attempt 3)
    succeeds.  (Worker threads share this process's memory; a forked
    SlowOneMax process worker has its own state and just succeeds.)"""

    attempts: dict = {}

    def evaluate(self):
        key = tuple(sorted((k, tuple(v)) for k, v in self.genes.items()))
        n = GlitchyOneMax.attempts.get(key, 0)
        GlitchyOneMax.attempts[key] = n + 1
        if n < 2:
            raise RuntimeError(f"transient glitch (attempt {n + 1})")
        return super().evaluate()


class PoisonOneMax(OneMax):
    """Permanently fails the all-zero genome (never heals)."""

    def evaluate(self):
        if sum(sum(g) for g in self.genes.values()) == 0:
            raise RuntimeError("poison genome")
        return super().evaluate()


class TestSearchFailureRecovery:
    """VERDICT r2 'do this' #3: a long search survives transient failures."""

    def test_six_generation_search_survives_glitches_and_sigkill(self):
        """A 6-generation distributed search completes despite (a) a worker
        whose evaluations fail transiently — exhausting broker attempts and
        raising JobFailed mid-search — and (b) a worker SIGKILLed mid-job;
        the GA history records the retry passes."""
        GlitchyOneMax.attempts = {}
        with DistributedPopulation(
            GlitchyOneMax, size=6, seed=11, port=0,
            additional_parameters={"nodes": (4, 4), "delay": 0.5},
            max_attempts=2, job_timeout=60.0, evaluate_retries=3,
        ) as pop:
            _, port = pop.broker_address
            ctx = multiprocessing.get_context("fork")
            victim = ctx.Process(target=_worker_process_main, args=(port,), daemon=True)
            victim.start()
            stop, _ = _start_worker_thread(GlitchyOneMax, port)
            result = {}

            def search():
                ga = GeneticAlgorithm(pop, seed=11)
                result["best"] = ga.run(6)
                result["history"] = ga.history

            st = threading.Thread(target=search, daemon=True)
            st.start()
            time.sleep(1.0)  # mid-search: victim is (or was) holding a job
            os.kill(victim.pid, signal.SIGKILL)
            victim.join(timeout=5.0)
            try:
                st.join(timeout=90.0)
                assert not st.is_alive(), "search did not survive the failures"
                assert result["best"].get_fitness() >= 8
                assert len(result["history"]) == 6
                retried = [h for h in result["history"] if h.get("evaluate_retries")]
                assert retried, "no generation recorded a retry pass"
                assert all(not h.get("penalized") for h in result["history"])
            finally:
                stop.set()

    def test_penalize_policy_keeps_search_alive_on_permanent_failure(self):
        """failed_policy='penalize': a permanently-failing individual gets
        the generation's worst fitness (uncached) instead of killing the
        search; eval_stats records it."""
        bad = {"S_1": (0,) * 6, "S_2": (0,) * 6}
        good = {"S_1": (1,) * 6, "S_2": (0, 1) * 3}
        inds = [
            PoisonOneMax(genes=g, additional_parameters={"nodes": (4, 4)})
            for g in (good, bad)
        ]
        with DistributedPopulation(
            PoisonOneMax, individual_list=inds,
            additional_parameters={"nodes": (4, 4)},
            port=0, max_attempts=1, job_timeout=30.0,
            evaluate_retries=1, failed_policy="penalize",
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(PoisonOneMax, port)
            try:
                completed = pop.evaluate()
                assert completed == 1  # only the healthy individual trained
                assert pop.eval_stats["penalized"] == 1
                assert pop.eval_stats["retries"] == 1
                good_fit = pop[0].get_fitness()
                assert pop[1].get_fitness() == good_fit  # worst observed = only observed
                # the penalty must NOT pollute the fitness cache
                key = pop._safe_cache_key(pop[1])
                assert key not in pop.fitness_cache
            finally:
                stop.set()

    def test_unknown_failed_policy_rejected(self):
        with pytest.raises(ValueError):
            DistributedPopulation(OneMax, size=2, port=0, failed_policy="shrug")


class TestDistributedGA:
    def test_full_search_over_workers(self):
        """BASELINE config #4's shape on one host: GA × broker × 2 workers."""
        with DistributedPopulation(OneMax, size=8, seed=6, port=0) as pop:
            _, port = pop.broker_address
            stops = [_start_worker_thread(OneMax, port)[0] for _ in range(2)]
            try:
                ga = GeneticAlgorithm(pop, seed=6)
                best = ga.run(3)
                assert best.get_fitness() >= 9  # (4,4) nodes → 12 bits max
                # clone_with preserved distribution across generations
                assert isinstance(ga.population, DistributedPopulation)
                assert ga.population.broker is pop.broker
            finally:
                for s in stops:
                    s.set()

    def test_grid_population_distributed(self):
        with DistributedGridPopulation(
            OneMax,
            genes_grid={"S_1": [(0,) * 6, (1,) * 6], "S_2": [(1,) * 6]},
            additional_parameters={"nodes": (4, 4)},
            port=0,
        ) as pop:
            assert len(pop) == 2
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port)
            try:
                fits = pop.get_fitnesses()
                assert sorted(fits) == [6.0, 12.0]
            finally:
                stop.set()


def test_clone_with_preserves_type_for_plain_population():
    pop = Population(OneMax, *DATA, size=3, seed=0)
    clone = pop.clone_with(list(pop.individuals))
    assert type(clone) is Population
    assert clone.rng is pop.rng


class CountingOneMax(OneMax):
    """Worker-side eval counter (worker threads share this process's memory)."""

    evals = 0

    def evaluate(self):
        CountingOneMax.evals += 1
        return super().evaluate()


class TestMasterSideDedup:
    def test_duplicate_genomes_ship_one_job(self):
        CountingOneMax.evals = 0
        dup = {"S_1": (1, 0, 1, 0, 1, 0), "S_2": (1, 1, 0, 0, 1, 0)}
        other = {"S_1": (0,) * 6, "S_2": (1,) * 6}
        inds = [
            CountingOneMax(genes=g, additional_parameters={"nodes": (4, 4)})
            for g in (dup, dup, dup, other)
        ]
        with DistributedPopulation(
            CountingOneMax,
            individual_list=inds,
            additional_parameters={"nodes": (4, 4)},
            port=0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(CountingOneMax, port)
            try:
                pop.evaluate()
            finally:
                stop.set()
        assert CountingOneMax.evals == 2  # 2 unique genomes, not 4 jobs
        assert all(ind.fitness_evaluated for ind in pop)
        assert pop[0].get_fitness() == pop[1].get_fitness() == pop[2].get_fitness()

    def test_cache_answers_next_generation_without_jobs(self):
        CountingOneMax.evals = 0
        g = {"S_1": (1, 1, 1, 0, 0, 0), "S_2": (0, 0, 0, 1, 1, 1)}
        inds = [CountingOneMax(genes=g, additional_parameters={"nodes": (4, 4)})]
        with DistributedPopulation(
            CountingOneMax,
            individual_list=inds,
            additional_parameters={"nodes": (4, 4)},
            port=0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(CountingOneMax, port)
            try:
                pop.evaluate()
                assert CountingOneMax.evals == 1
                # next generation re-derives the same genome: cache, no wire
                stop.set()  # no workers alive — a shipped job would hang
                child = pop.spawn(genes=g)
                nxt = pop.clone_with([child])
                nxt.job_timeout = 5.0
                nxt.evaluate()
                assert child.get_fitness() == pop[0].get_fitness()
                assert CountingOneMax.evals == 1
            finally:
                stop.set()


class TestBrokerEdgeCases:
    def test_gather_timeout_applies_partial_results(self):
        """A straggler timeout keeps the fitnesses that DID arrive."""
        with DistributedPopulation(
            SlowOneMax, size=3, seed=8, port=0, job_timeout=2.5,
            additional_parameters={"delay": 0.2},
        ) as pop:
            _, port = pop.broker_address
            # One worker, capacity 1, allowed to finish exactly TWO jobs,
            # then it exits — the third job can never finish.
            t = threading.Thread(
                target=_run_worker,
                args=(SlowOneMax, port),
                kwargs={"max_jobs": 2},
                daemon=True,
            )
            t.start()
            with pytest.raises(TimeoutError):
                pop.evaluate()
            evaluated = [ind for ind in pop if ind.fitness_evaluated]
            assert len(evaluated) == 2  # finished work survived the timeout
            # retry reships ONLY the unfinished individual
            stop, _ = _start_worker_thread(SlowOneMax, port)
            try:
                assert pop.evaluate() == 1
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                stop.set()

    def test_oversized_payload_raises_in_submit(self):
        """Size validation happens in the caller's thread, not the loop."""
        from gentun_tpu.distributed.protocol import MAX_MESSAGE_BYTES, ProtocolError

        broker = JobBroker(port=0).start()
        try:
            huge = {"genes": {"S_1": "x" * (MAX_MESSAGE_BYTES + 10)}, "additional_parameters": {}}
            with pytest.raises(ProtocolError):
                broker.submit({"j": huge})
            assert broker._payloads == {}  # nothing was enqueued
        finally:
            broker.stop()

    def test_exact_max_size_frame_round_trips(self):
        """A payload of exactly MAX_MESSAGE_BYTES passes encode() and must
        survive decode() too — the framing newline no longer tips the frame
        over the size check (ADVICE r3 boundary fix)."""
        from gentun_tpu.distributed.protocol import MAX_MESSAGE_BYTES, decode, encode

        probe = {"type": "result", "job_id": "j", "fitness": 1.0, "pad": ""}
        overhead = len(encode(probe)) - 1  # minus the newline
        probe["pad"] = "x" * (MAX_MESSAGE_BYTES - overhead)
        frame = encode(probe)
        assert len(frame) == MAX_MESSAGE_BYTES + 1  # payload + newline
        assert decode(frame)["pad"] == probe["pad"]

    def test_large_batch_splits_into_multiple_frames_and_completes(self):
        """Batches over the soft cap arrive as several `jobs` frames; a real
        worker consumes them frame by frame and every job completes."""
        from gentun_tpu.distributed.protocol import MAX_MESSAGE_BYTES

        # ~1.3 MB of padding per job => 4 jobs exceed the 2 MB soft cap.
        pad = "p" * (MAX_MESSAGE_BYTES // 3)
        inds = [
            OneMax(genes={"S_1": (1, 0, i % 2, 0, 1, 0), "S_2": (1,) * 6},
                   additional_parameters={"nodes": (4, 4), "pad": pad})
            for i in range(4)
        ]
        with DistributedPopulation(
            OneMax,
            individual_list=inds,
            additional_parameters={"nodes": (4, 4), "pad": pad},
            port=0,
            job_timeout=30.0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port, capacity=4)
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
            finally:
                stop.set()


class TestWorkerCli:
    def test_module_entrypoint_serves_jobs(self):
        """`python -m gentun_tpu.distributed.worker` is a functioning worker:
        it loads its own dataset, serves the master's jobs, and exits at
        --max-jobs."""
        import subprocess
        import sys

        from gentun_tpu import BoostingIndividual

        with DistributedPopulation(
            BoostingIndividual, size=2, seed=9, port=0,
            additional_parameters={"kfold": 2},
            job_timeout=120.0,
        ) as pop:
            _, port = pop.broker_address
            repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
            env = dict(os.environ, PYTHONPATH=repo)
            proc = subprocess.Popen(
                [sys.executable, "-m", "gentun_tpu.distributed.worker",
                 "--host", "127.0.0.1", "--port", str(port),
                 "--species", "boosting", "--dataset", "uci-binary",
                 "--max-jobs", "2"],
                env=env, cwd=repo,
            )
            try:
                pop.evaluate()
                assert all(ind.fitness_evaluated for ind in pop)
                assert all(0.0 <= ind.get_fitness() <= 1.0 for ind in pop)
                assert proc.wait(timeout=30) == 0  # exited cleanly at --max-jobs
            finally:
                if proc.poll() is None:
                    proc.kill()


class TestMasterCrashResume:
    """SURVEY.md §5: 'Master death is unrecoverable' in the reference — the
    rebuild beats it: checkpoint + DistributedPopulation survive a master
    crash, workers reconnect to the reborn master, and the completed search
    is bit-compatible with an uninterrupted one (VERDICT r1 item #8)."""

    def test_master_crash_resume_completes_bit_compatibly(self, tmp_path):
        from gentun_tpu.utils import Checkpointer

        path = str(tmp_path / "distributed-ckpt.json")
        # A FIXED port (picked free) so the surviving worker's reconnect
        # loop can find the reborn master; ephemeral port=0 would change.
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        port = probe.getsockname()[1]
        probe.close()

        # Uninterrupted reference: single-process, same seeds (OneMax fitness
        # is pure, so local and remote evaluation agree exactly).
        ga_full = GeneticAlgorithm(Population(OneMax, *DATA, size=6, seed=42), seed=7)
        ga_full.run(5)

        # Act 1: distributed master + worker; checkpoint; crash after gen 2.
        pop_a = DistributedPopulation(OneMax, size=6, seed=42, host="127.0.0.1", port=port)
        stop, _ = _start_worker_thread(OneMax, port)
        try:
            ga_a = GeneticAlgorithm(pop_a, seed=7)
            ga_a.set_checkpointer(Checkpointer(path))
            ga_a.run(2)
        finally:
            # the "crash": broker listener dies with the master process;
            # the worker survives and enters its reconnect loop
            ga_a.population.close()
            pop_a.close()
        del ga_a, pop_a

        # Act 2: reborn master on the SAME port resumes from the checkpoint.
        pop_b = DistributedPopulation(OneMax, size=6, seed=0, host="127.0.0.1", port=port)
        try:
            ga_b = GeneticAlgorithm(pop_b, seed=0)
            assert Checkpointer(path).resume(ga_b)
            assert ga_b.generation == 2
            ga_b.run(3)  # worker reconnected and served these generations
            full = [(ind.get_genes(), ind.get_fitness()) for ind in ga_full.population]
            resumed = [(ind.get_genes(), ind.get_fitness()) for ind in ga_b.population]
            assert full == resumed
        finally:
            ga_b.population.close()
            pop_b.close()
            stop.set()


class TestBrokerOwnership:
    def test_close_on_clone_stops_embedded_broker(self):
        pop = DistributedPopulation(OneMax, size=2, seed=0, port=0)
        clone = pop.clone_with([pop[0].copy()])
        assert clone._owns_broker  # co-owns: GA holds only clones after gen 1
        clone.close()
        assert not pop.broker._started.is_set()
        pop.close()  # idempotent: original closing after the clone is safe

    def test_external_broker_never_stopped_by_clones(self):
        broker = JobBroker(port=0).start()
        try:
            pop = DistributedPopulation(OneMax, size=2, seed=0, broker=broker)
            clone = pop.clone_with([pop[0].copy()])
            assert not clone._owns_broker
            clone.close()
            pop.close()
            assert broker._started.is_set()  # still running
        finally:
            broker.stop()


class TestFleetChips:
    """VERDICT r3 item 3: the per-chip metric divides by the fleet's chips."""

    def test_logged_metric_divides_by_advertised_chips(self):
        with DistributedPopulation(
            SlowOneMax, size=4, seed=0, port=0,
            additional_parameters={"delay": 0.1},
        ) as pop:
            _, port = pop.broker_address
            stop = threading.Event()
            threading.Thread(
                target=lambda: GentunClient(
                    SlowOneMax, *DATA, port=port, capacity=4, n_chips=4,
                    heartbeat_interval=0.2, reconnect_delay=0.1,
                ).work(stop_event=stop),
                daemon=True,
            ).start()
            try:
                ga = GeneticAlgorithm(pop, seed=0)
                ga.evolve_population()
                rec = ga.history[0]
                assert rec["n_chips"] == 4
                # the logged metric is evaluated/hour divided by the fleet's
                # chip total, not by the master's (jax-less) local count of 1
                per_cluster = rec["evaluated"] / (rec["eval_wall_s"] / 3600.0)
                assert rec["individuals_per_hour_per_chip"] == pytest.approx(
                    per_cluster / 4, rel=0.05
                )
            finally:
                stop.set()

    def test_non_jax_species_advertises_one_chip(self):
        with DistributedPopulation(OneMax, size=2, seed=0, port=0) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port)
            try:
                pop.evaluate()
                assert pop.eval_stats["n_chips"] == 1
                assert pop.broker.fleet_chips() == 1
            finally:
                stop.set()

    def test_fleet_chips_sums_across_workers(self):
        with DistributedPopulation(OneMax, size=4, seed=0, port=0) as pop:
            _, port = pop.broker_address
            stop = threading.Event()
            for chips in (3, 5):
                threading.Thread(
                    target=lambda c=chips: GentunClient(
                        OneMax, *DATA, port=port, capacity=2, n_chips=c,
                        heartbeat_interval=0.2, reconnect_delay=0.1,
                    ).work(stop_event=stop),
                    daemon=True,
                ).start()
            try:
                deadline = time.monotonic() + 5.0
                while pop.broker.fleet_chips() != 8 and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert pop.broker.fleet_chips() == 8
                pop.evaluate()
                assert pop.eval_stats["n_chips"] == 8
            finally:
                stop.set()

    def test_worker_exiting_after_final_result_still_counts(self):
        """ADVICE r4: the per-chip denominator must survive a worker that
        delivers its last result and disconnects before the post-sweep
        snapshot.  A --max-jobs worker exits the instant its results are
        sent; with only the end-of-sweep fleet_chips() its 4 chips would
        collapse to 1."""
        with DistributedPopulation(OneMax, size=4, seed=0, port=0) as pop:
            _, port = pop.broker_address
            t = threading.Thread(
                target=lambda: GentunClient(
                    OneMax, *DATA, port=port, capacity=4, n_chips=4,
                    heartbeat_interval=0.2, reconnect_delay=0.1,
                ).work(max_jobs=4),
                daemon=True,
            )
            t.start()
            pop.evaluate()
            t.join(timeout=10)  # worker already gone (or going)
            assert pop.eval_stats["n_chips"] == 4

    def test_single_process_record_unchanged(self):
        """Non-distributed populations keep the local-chip denominator
        (whatever the already-initialized backend reports in this process —
        other tests in the suite may have touched the 8-device CPU mesh)."""
        from gentun_tpu.algorithms import _initialized_chip_count

        pop = Population(OneMax, *DATA, size=3, seed=0)
        ga = GeneticAlgorithm(pop, seed=0)
        ga.evolve_population()
        assert ga.history[0]["n_chips"] == _initialized_chip_count()


class TestDistributedFitnessStore:
    """VERDICT r3 item 7: the flagship path reuses cross-run measurements."""

    def test_second_run_over_same_genomes_ships_zero_jobs(self, tmp_path):
        store = str(tmp_path / "onemax.fitness.json")
        genes = None
        # First search: evaluates over a real worker, saves the store on close.
        with DistributedPopulation(
            OneMax, size=4, seed=11, port=0, fitness_store=store,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port)
            try:
                shipped = pop.evaluate()
                assert shipped > 0
                genes = [ind.get_genes() for ind in pop]
                fits = [ind.get_fitness() for ind in pop]
            finally:
                stop.set()
        assert os.path.exists(store)

        # Second search, same genomes, NO workers connected: every fitness
        # must come from the store — evaluate() ships zero jobs (it would
        # block forever otherwise, so the 10s timeout doubles as the proof).
        inds = [OneMax(genes=g) for g in genes]
        with DistributedPopulation(
            OneMax, individual_list=inds, fitness_store=store, port=0,
            job_timeout=10.0,
        ) as pop2:
            assert pop2.evaluate() == 0
            assert [ind.get_fitness() for ind in pop2] == fits

    def test_worker_side_store_answers_without_training(self, tmp_path):
        """VERDICT r4 item 7: a WORKER given --fitness-store answers repeated
        jobs from the store instead of retraining.  The stored fitness is a
        sentinel no real OneMax evaluation could produce, so the returned
        value proves the store (not training) answered."""
        from gentun_tpu.utils.fitness_store import save_fitness_cache

        store = str(tmp_path / "worker.fitness.json")
        probe = OneMax(genes={"S_1": (1, 0, 1, 1, 1, 1), "S_2": (0, 1, 0, 0, 0, 0)})
        sentinel = 4242.5  # OneMax fitness is a bit count — can't be this
        save_fitness_cache({probe.cache_key(): sentinel}, store)

        # Master WITHOUT a store: reuse must happen on the worker side.
        with DistributedPopulation(
            OneMax, individual_list=[OneMax(genes=probe.get_genes())], port=0,
        ) as pop:
            _, port = pop.broker_address
            stop, _ = _start_worker_thread(OneMax, port, fitness_store=store)
            try:
                assert pop.evaluate() == 1  # the job WAS shipped...
                assert pop[0].get_fitness() == sentinel  # ...but not trained
            finally:
                stop.set()

    def test_worker_store_refused_for_multihost(self, tmp_path):
        with pytest.raises(ValueError, match="multihost"):
            GentunClient(OneMax, *DATA, multihost=True,
                         fitness_store=str(tmp_path / "x.json"))

    def test_in_memory_measurement_beats_stored_value(self, tmp_path):
        from gentun_tpu.utils.fitness_store import save_fitness_cache

        store = str(tmp_path / "seed.fitness.json")
        probe = OneMax(genes={"S_1": (1,) * 6, "S_2": (0,) * 6})
        save_fitness_cache({probe.cache_key(): -99.0}, store)
        live = {probe.cache_key(): 6.0}
        pop = DistributedPopulation(
            OneMax, individual_list=[OneMax(genes=probe.get_genes())],
            fitness_store=store, fitness_cache=live, port=0,
        )
        try:
            assert pop.evaluate() == 0
            assert pop[0].get_fitness() == 6.0
        finally:
            pop.close()

    def test_clone_carries_store_and_close_saves(self, tmp_path):
        from gentun_tpu.utils.fitness_store import load_fitness_cache

        store = str(tmp_path / "clone.fitness.json")
        pop = DistributedPopulation(OneMax, size=2, seed=3, port=0, fitness_store=store)
        _, port = pop.broker_address
        stop, _ = _start_worker_thread(OneMax, port)
        try:
            pop.evaluate()
            clone = pop.clone_with([ind.copy() for ind in pop])
            assert clone.fitness_store == store
            clone.close()  # the GA hands back clones; closing one must save
            assert len(load_fitness_cache(store)) > 0
        finally:
            stop.set()
            pop.close()


class TestBackendAdvertisement:
    """ADVICE r3: a mixed fleet scoring one generation with two different
    estimators must be warned about at the master."""

    def test_heterogeneous_fleet_warns(self, caplog):
        class BackendA(OneMax):
            model_cls = type("XgboostModel", (), {})

        class BackendB(OneMax):
            model_cls = type("BoostingModel", (), {})

        import logging as _logging

        with DistributedPopulation(OneMax, size=2, seed=0, port=0) as pop:
            _, port = pop.broker_address
            stop = threading.Event()
            with caplog.at_level(_logging.WARNING, logger="gentun_tpu.distributed"):
                for species in (BackendA, BackendB):
                    threading.Thread(
                        target=lambda s=species: GentunClient(
                            s, *DATA, port=port, heartbeat_interval=0.2,
                            reconnect_delay=0.1,
                        ).work(stop_event=stop),
                        daemon=True,
                    ).start()
                try:
                    deadline = time.monotonic() + 5.0
                    while time.monotonic() < deadline and not any(
                        "heterogeneous fitness backends" in r.message for r in caplog.records
                    ):
                        time.sleep(0.05)
                    assert any(
                        "heterogeneous fitness backends" in r.message for r in caplog.records
                    )
                finally:
                    stop.set()

    def test_homogeneous_fleet_quiet(self, caplog):
        import logging as _logging

        with DistributedPopulation(OneMax, size=2, seed=0, port=0) as pop:
            _, port = pop.broker_address
            stops = []
            with caplog.at_level(_logging.WARNING, logger="gentun_tpu.distributed"):
                try:
                    for _ in range(2):
                        stops.append(_start_worker_thread(OneMax, port)[0])
                    pop.evaluate()
                    assert not any(
                        "heterogeneous fitness backends" in r.message for r in caplog.records
                    )
                finally:
                    for s in stops:
                        s.set()


class TestWorkerCliGuards:
    """ADVICE r3: non-positive --n must be rejected loudly, not yield an
    empty or silently truncated dataset."""

    @pytest.mark.parametrize("bad_n", ["0", "-5"])
    def test_non_positive_n_rejected(self, bad_n):
        from gentun_tpu.distributed.worker import main as worker_main

        with pytest.raises(SystemExit, match="must be positive"):
            worker_main([
                "--species", "boosting", "--dataset", "uci-binary",
                "--n", bad_n, "--max-jobs", "1",
            ])


class TestFinalResultsNotLostOnExit:
    """Regression (found by the multihost CNN e2e test): a worker exiting
    right after its last batch used to close the socket with unread
    broker frames in its receive buffer, turning close() into an RST that
    destroyed the still-in-flight result frames.  Heartbeat replies are
    gone and the clean-exit path now FIN-drains (``_graceful_close``), so
    every result of the final batch must arrive."""

    def test_worker_exit_after_final_batch_delivers_all_results(self):
        with DistributedPopulation(
            SlowOneMax, size=6, seed=2, port=0,
            additional_parameters={"delay": 0.5}, job_timeout=60.0,
        ) as pop:
            _, port = pop.broker_address
            # Tiny heartbeat interval: many pings pile up during the slow
            # batch (the old pong replies would have sat unread); max_jobs
            # makes the worker exit the instant the batch is replied.
            worker = GentunClient(
                SlowOneMax, *DATA, port=port, capacity=6,
                heartbeat_interval=0.02, reconnect_delay=0.1,
            )
            t = threading.Thread(target=lambda: worker.work(max_jobs=6), daemon=True)
            t.start()
            assert pop.evaluate() == 6  # every result of the final batch arrived
            t.join(timeout=10.0)
            assert not t.is_alive()


class TestDistributedFitnessPurity:
    """Distributed evaluation must be bit-identical to local evaluation.

    The worker trains whatever job batch the broker hands it (capacity
    chunks, arrival order) — compositions the local ``evaluate()`` never
    produces.  Content-hash PRNG keys (``models/cnn._genome_hashes``)
    make fitness a pure function of (architecture, config, seed), so the
    transport layer cannot move a measurement."""

    def test_capacity_chunked_worker_matches_local_bitwise(self):
        from gentun_tpu import GeneticCnnIndividual

        rng = np.random.default_rng(3)
        protos = rng.normal(size=(3, 8, 8, 1)).astype(np.float32)
        yv = rng.integers(0, 3, size=96).astype(np.int32)
        xv = (protos[yv] + 0.25 * rng.normal(size=(96, 8, 8, 1))).astype(np.float32)
        params = dict(nodes=(3,), kernels_per_layer=(6,), kfold=2, epochs=(1,),
                      learning_rate=(0.05,), batch_size=32, dense_units=16,
                      compute_dtype="float32", seed=0)

        local = Population(GeneticCnnIndividual, x_train=xv, y_train=yv,
                           size=6, seed=5, additional_parameters=params)
        local.evaluate()
        local_fits = {ind.cache_key(): ind.get_fitness() for ind in local}

        # capacity=2: the worker trains 2-wide chunks — different program
        # shapes AND different batch compositions than the local one-shot
        with DistributedPopulation(GeneticCnnIndividual, size=6, seed=5,
                                   additional_parameters=params, port=0) as dist:
            _, port = dist.broker_address
            stop = threading.Event()
            t = threading.Thread(
                target=lambda: GentunClient(
                    GeneticCnnIndividual, xv, yv, host="127.0.0.1", port=port,
                    capacity=2, heartbeat_interval=0.2, reconnect_delay=0.1,
                ).work(stop_event=stop),
                daemon=True,
            )
            t.start()
            try:
                dist.evaluate()
                assert all(ind.fitness_evaluated for ind in dist)
                for ind in dist:
                    assert ind.get_fitness() == local_fits[ind.cache_key()], (
                        "distributed fitness differs from local for the same "
                        "architecture under the same config+seed"
                    )
            finally:
                stop.set()
                t.join(timeout=15.0)


class TestCleanShutdown:
    def test_stop_drains_connection_handlers(self):
        """stop() must cancel and DRAIN the per-connection handler
        coroutines before stopping the loop.  Stopping with handlers
        parked on readline() left pending tasks (asyncio logged "Task was
        destroyed but it is pending!" at master exit) and — the
        deterministic symptom asserted here — skipped the handlers'
        finally-block cleanup, leaving the dead connection registered in
        the worker table after shutdown."""
        import json
        import socket

        broker = JobBroker(port=0).start()
        host, port = broker.address
        s = socket.create_connection((host, port))
        try:
            s.sendall((json.dumps({"type": "hello", "worker_id": "w1",
                                   "token": None, "capacity": 1,
                                   "n_chips": 1, "backend": "test"}) + "\n").encode())
            deadline = time.monotonic() + 5.0
            # fleet_chips() floors at 1, so wait on the worker table itself
            while not broker._workers and time.monotonic() < deadline:
                time.sleep(0.05)  # handler task now parked on readline()
            assert broker._workers  # hello processed, handler registered
            broker.stop()
            # the handler's finally ran during shutdown: worker table empty
            assert broker._workers == {}
        finally:
            s.close()
