"""Multi-host (multi-controller) tests: one worker spanning processes.

VERDICT r2 "do this" #1: the north-star topology is a v5e-32 — an 8-host
slice owned by ONE worker.  No multi-host TPU exists in CI, so these tests
form real 2- and 4-process jax clusters over CPU (8 global virtual devices
split across the processes — the same mechanism as ``conftest.py``) and
prove:

- the sharded population CV runs under multi-controller execution and
  matches the single-process result on the same logical mesh;
- the leader/follower worker loop (process 0 owns the broker connection,
  payload broadcast over the device fabric) completes real jobs end to end.

The children run in subprocesses (``_multihost_child.py``) because a jax
cluster needs one process per "host"; the parent uses its own in-process
8-device CPU backend for the single-process reference run.
"""

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_multihost_child.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_cluster(mode: str, out_path: str, extra_args=(), nproc: int = 2,
                   total_devices: int = 8):
    """Launch an nproc-process jax CPU cluster of _multihost_child.py.

    ``total_devices`` global devices split across nproc processes — the
    classic tests run 8 (the conftest mesh size; 2×4 mirrors "few hosts,
    several chips each"), the v5e-32-shape test runs 32 as 8×4.
    """
    coord_port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={total_devices // nproc}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, CHILD, mode, str(pid), str(nproc), str(coord_port), out_path,
             *map(str, extra_args)],
            env=env,
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        for pid in range(nproc)
    ]
    return procs


# Environment limitations (vs regressions): a cluster child that dies with
# one of these signatures means THIS interpreter/jaxlib/box cannot run the
# multi-process jax topology under test — skip with the precise reason,
# never fail-by-environment.  Any other child death is a real failure, and
# it wins over env signatures in peers: when one child hits a genuine bug,
# the survivors abort with gloo connection resets, so a skip is only valid
# if EVERY failed child shows an environment signature.
_ENV_SKIP_PATTERNS = (
    ("Multiprocess computations aren't implemented",
     "this jaxlib's CPU backend has no cross-process collectives "
     "implementation (jax_cpu_collectives_implementation/gloo unavailable)"),
    ("gloo::EnforceNotMet",
     "jaxlib's gloo CPU collectives crashed inside the cluster child "
     "(XLA:CPU thunk-runtime incompatibility, see "
     "parallel/multihost.py::_enable_cpu_collectives)"),
    ("external/gloo/gloo/transport/tcp",
     "jaxlib's gloo TCP collectives lost a peer mid-collective (abort "
     "cascade — seen with 8 ranks contending for this box's single CPU "
     "core)"),
    # The coordination-service flavor of the same cascade: a child that
    # never errored itself is torn down by jax.distributed because a
    # peer died ("another task died").  Harmless to recognize — a child
    # with a REAL bug dies with its own traceback, lacks this line, and
    # still wins over every peer's signature (see _resolve_failures).
    ("Terminating process because the JAX distributed service detected "
     "fatal errors",
     "jax coordination service tore this child down after a peer died "
     "(peer-abort cascade; the peers carried gloo environment "
     "signatures)"),
)


def _env_limit_reason(out: str):
    for needle, why in _ENV_SKIP_PATTERNS:
        if needle in out:
            return why
    return None


def _resolve_failures(failures):
    """``failures`` is ``[(rc, output), ...]`` for every child that died
    nonzero on its own.  Any failure WITHOUT an environment signature is a
    real regression and raises with that child's output; only when all of
    them carry one does the test skip."""
    reasons = []
    for rc, out in failures:
        why = _env_limit_reason(out)
        if why is None:
            raise AssertionError(f"cluster child died rc={rc}:\n{out[-3000:]}")
        reasons.append(why)
    if reasons:
        pytest.skip(f"multi-process jax unsupported in this environment: {reasons[0]}")


def _check_alive(procs):
    """While waiting on a cluster: a child already dead of an environment
    limitation skips the test immediately instead of timing the wait out;
    any other dead child fails it with the child's output."""
    if all(p.poll() is None or p.returncode == 0 for p in procs):
        return
    time.sleep(1.0)  # let peer-abort cascades land before sampling outputs
    killed = [p for p in procs if p.poll() is None]
    for p in killed:
        p.kill()
    failures = []
    for p in procs:
        out, _ = p.communicate()
        text = out.decode(errors="replace") if out else ""
        if p.returncode != 0 and p not in killed:
            failures.append((p.returncode, text))
    _resolve_failures(failures)


def _join(procs, timeout: float):
    deadline = time.monotonic() + timeout
    outs = []
    for p in procs:
        remaining = max(1.0, deadline - time.monotonic())
        try:
            out, _ = p.communicate(timeout=remaining)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    _resolve_failures(
        [(p.returncode, out) for p, out in zip(procs, outs) if p.returncode != 0])
    return outs


@pytest.fixture(scope="module")
def single_process_reference():
    """The (2,4)-mesh single-process CV result, computed once per module —
    it is independent of how many processes the cluster splits into."""
    sys.path.insert(0, os.path.dirname(CHILD))
    try:
        from _multihost_child import run_cv
    finally:
        sys.path.pop(0)
    from gentun_tpu.parallel.mesh import auto_mesh

    mesh = auto_mesh(pop_axis=2, data_axis=4)
    if mesh is None:
        pytest.skip("single-process reference needs the 8-virtual-device "
                    "CPU environment (conftest XLA_FLAGS)")
    return np.asarray(run_cv(mesh), dtype=np.float32)


@pytest.mark.parametrize("nproc", [2, 4])
def test_cluster_cv_matches_single_process(tmp_path, nproc, single_process_reference):
    """nproc processes × (8/nproc) virtual CPU devices = one 8-device
    cluster running the REAL sharded CV path; the leader's accuracies must
    match this process's single-process run on the same logical (2, 4)
    mesh.  4 processes exercises the many-hosts shape of a pod slice."""
    want = single_process_reference
    out_path = str(tmp_path / "accs.json")
    procs = _spawn_cluster("cv", out_path, nproc=nproc)
    _join(procs, timeout=480.0)
    with open(out_path) as f:
        got = np.asarray(json.load(f), dtype=np.float32)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_cluster_cv_matches_single_process_v5e32_shape(tmp_path):
    """The NORTH-STAR topology's exact shape (VERDICT r4 item 3): 32 global
    devices on an (8, 4) pop×data mesh, as the v5e-32's 8 hosts × 4 chips.
    The 8-process cluster run must match a 1-process run over the same 32
    logical devices — same mesh factoring, same collective shapes, only the
    process boundaries differ."""
    ref_path = str(tmp_path / "ref.json")
    got_path = str(tmp_path / "got.json")
    # Reference first (1 process × 32 virtual devices): also a jax cluster,
    # just a trivial one, so the code path is identical end to end.
    _join(_spawn_cluster("cv32", ref_path, nproc=1, total_devices=32), timeout=480.0)
    _join(_spawn_cluster("cv32", got_path, nproc=8, total_devices=32), timeout=480.0)
    with open(ref_path) as f:
        want = np.asarray(json.load(f), dtype=np.float32)
    with open(got_path) as f:
        got = np.asarray(json.load(f), dtype=np.float32)
    assert want.shape == (8,)  # 8 genomes filled the 8-row population axis
    assert np.isfinite(want).all()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_multihost_worker_completes_jobs(tmp_path):
    """Full leader/follower worker: process 0 consumes from the broker,
    broadcasts batches over the device fabric, every rank evaluates, only
    the leader replies — and the master's barrier completes."""
    from gentun_tpu.distributed import JobBroker

    rng = np.random.default_rng(7)
    genomes = [
        {"S_1": [int(b) for b in rng.integers(0, 2, 6)],
         "S_2": [int(b) for b in rng.integers(0, 2, 6)]}
        for _ in range(4)
    ]
    payloads = {
        f"job-{i}": {"genes": g, "additional_parameters": {"nodes": (4, 4)}}
        for i, g in enumerate(genomes)
    }
    broker = JobBroker(port=0).start()
    procs = []
    try:
        _, port = broker.address
        out_path = str(tmp_path / "worker.json")
        procs = _spawn_cluster("worker", out_path, extra_args=(port, len(payloads)))
        deadline = time.monotonic() + 240.0
        while not broker._workers and time.monotonic() < deadline:
            _check_alive(procs)  # env-limited child death → skip, not timeout
            time.sleep(0.1)
        broker.submit(payloads)
        results = broker.gather(list(payloads), timeout=300.0)
        expected = {
            f"job-{i}": float(sum(sum(g) for g in genomes[i].values()))
            for i in range(len(genomes))
        }
        assert results == expected
        _join(procs, timeout=120.0)
        # Both ranks evaluated every job (lockstep), one rank replied.
        with open(out_path + ".rank0") as f:
            assert json.load(f)["jobs_done"] == len(payloads)
        with open(out_path + ".rank1") as f:
            assert json.load(f)["jobs_done"] == len(payloads)
    finally:
        for p in procs:  # never leak the cluster on a gather/assert failure
            if p.poll() is None:
                p.kill()
        broker.stop()


def test_follower_exits_bounded_when_leader_sigkilled(tmp_path):
    """VERDICT r3 item 8: SIGKILL the leader rank (no shutdown sentinel) —
    the follower must exit nonzero within a bounded time instead of hanging
    until the runtime's collective timeout.  Code 17 is the leader
    watchdog's signature (multihost.start_leader_watchdog); a fast
    collective-layer failure may occasionally beat the watchdog, which is
    an equally bounded nonzero exit."""
    from gentun_tpu.distributed import JobBroker

    broker = JobBroker(port=0).start()
    procs = []
    try:
        _, port = broker.address
        out_path = str(tmp_path / "wd.json")
        procs = _spawn_cluster("worker", out_path, extra_args=(port, 100))
        deadline = time.monotonic() + 240.0
        while not broker._workers and time.monotonic() < deadline:
            _check_alive(procs)
            time.sleep(0.1)
        assert broker._workers, "leader never connected to the broker"
        time.sleep(1.0)  # follower is in its broadcast loop, watchdog armed
        procs[0].kill()  # SIGKILL: the sentinel can never be sent
        t0 = time.monotonic()
        out, _ = procs[1].communicate(timeout=60.0)
        elapsed = time.monotonic() - t0
        assert procs[1].returncode not in (0, None), out.decode(errors="replace")[-2000:]
        assert elapsed < 45.0, f"follower took {elapsed:.1f}s to notice leader death"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        broker.stop()


def test_multihost_worker_real_cnn_matches_single_process(tmp_path):
    """VERDICT r3 item 4 — the v5e-32 worker's exact composition, end to
    end: master barrier → broker jobs → leader broadcast over the device
    fabric → ``Population.evaluate`` → sharded ``GeneticCnnModel`` CV
    across a 2-process cluster.  Fitnesses must match a single-process
    evaluation of the same genomes under the same (auto) mesh logic, and
    the worker must advertise the slice's full chip count."""
    sys.path.insert(0, os.path.dirname(CHILD))
    try:
        from _multihost_child import build_small_cnn_workload
    finally:
        sys.path.pop(0)
    from gentun_tpu import GeneticCnnIndividual, Population
    from gentun_tpu.distributed import JobBroker

    x, y, genomes, config = build_small_cnn_workload()
    # Share one persistent XLA cache between this process and the cluster
    # children so they can load what the reference run compiled instead of
    # recompiling under in-suite CPU contention.
    config = dict(config, cache_dir=str(tmp_path / "xla-cache"))
    # Reference = the SINGLE-PROCESS Population.evaluate path (exactly what
    # the worker runs): this includes the canonical-architecture dedup, so
    # an isomorphic pair in the genome set — deliberately present — must
    # share one fitness on both sides.
    ref_pop = Population(
        GeneticCnnIndividual,
        x_train=x,
        y_train=y,
        individual_list=[
            GeneticCnnIndividual(x_train=x, y_train=y, genes=g,
                                 additional_parameters=dict(config))
            for g in genomes
        ],
        additional_parameters=dict(config),
    )
    ref_pop.evaluate()
    want = np.asarray([ind.get_fitness() for ind in ref_pop], dtype=np.float32)

    payloads = {
        f"cnn-{i}": {
            "genes": {k: list(v) for k, v in g.items()},
            "additional_parameters": {
                k: (list(v) if isinstance(v, tuple) else v) for k, v in config.items()
            },
        }
        for i, g in enumerate(genomes)
    }
    # Long heartbeat: a contended compile can starve the leader's ping
    # thread past the 15 s default, and a spurious mid-compile reap turns
    # one slow evaluation into several.
    broker = JobBroker(port=0, heartbeat_timeout=300.0).start()
    procs = []
    try:
        _, port = broker.address
        out_path = str(tmp_path / "cnn_worker.json")
        procs = _spawn_cluster("worker-cnn", out_path, extra_args=(port, len(payloads)))
        # One logical worker spanning the whole 8-device slice advertises
        # all of it in its hello (VERDICT r3 item 3 on the real species);
        # check while it is connected — it disconnects after max_jobs.
        deadline = time.monotonic() + 600.0
        while broker.fleet_chips() != 8 and time.monotonic() < deadline:
            _check_alive(procs)
            time.sleep(0.2)
        assert broker.fleet_chips() == 8
        broker.submit(payloads)
        # Generous: suite runs share the host CPU with other XLA compiles.
        results = broker.gather(list(payloads), timeout=900.0)
        got = np.asarray([results[f"cnn-{i}"] for i in range(len(genomes))], dtype=np.float32)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        _join(procs, timeout=120.0)
        with open(out_path + ".rank1") as f:
            assert json.load(f)["jobs_done"] == len(payloads)  # lockstep rank
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        broker.stop()
