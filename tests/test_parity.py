"""Offline tests for the real-data parity harness (VERDICT r3 item 5).

No real MNIST/CIFAR exists in this environment, so these tests exercise
the harness's IO, skip, pass and fail logic with synthetic npz archives —
the measurement itself only runs on a networked user's machine.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts", "parity.py"
)


@pytest.fixture
def parity():
    spec = importlib.util.spec_from_file_location("parity_script", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_archive(d, name, n=160, hwc=(28, 28, 1), classes=10, separable=True):
    rng = np.random.default_rng(0)
    if separable:  # class prototypes: tiny nets learn this fast
        protos = rng.normal(size=(classes, *hwc)).astype(np.float32)
        y = rng.integers(0, classes, size=n).astype(np.int32)
        x = protos[y] + 0.3 * rng.normal(size=(n, *hwc)).astype(np.float32)
    else:
        x = rng.normal(size=(n, *hwc)).astype(np.float32)
        y = rng.integers(0, classes, size=n).astype(np.int32)
    np.savez(os.path.join(d, f"{name}.npz"), x=x, y=y)


TINY = [
    "--datasets", "mnist", "--generations", "1", "--pop", "3",
    "--proxy-epochs", "1", "--full-epochs", "2", "--kernels", "4", "4",
    "--dense-units", "16", "--batch-size", "32",
]


def test_skip_without_archives_is_loud(parity, tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("GENTUN_TPU_DATA", str(tmp_path / "empty"))
    rc = parity.main(TINY + ["--out", str(tmp_path / "PARITY.md")])
    out = capsys.readouterr().out
    assert rc == 3
    assert "PARITY SKIPPED" in out and "NOT a pass" in out
    assert not os.path.exists(tmp_path / "PARITY.md")  # nothing measured


def test_pass_band_writes_parity_md(parity, tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("GENTUN_TPU_DATA", str(tmp_path))
    _write_archive(str(tmp_path), "mnist")
    out_md = str(tmp_path / "PARITY.md")
    rc = parity.main(TINY + ["--band", "0.0", "--out", out_md])
    assert rc == 0
    assert "PASS" in capsys.readouterr().out
    with open(out_md) as f:
        md = f.read()
    assert "| mnist | PASS |" in md
    with open(str(tmp_path / "PARITY.json")) as f:
        rows = json.load(f)
    assert rows[0]["status"] == "PASS"
    assert rows[0]["source"].endswith("mnist.npz")
    assert 0.0 <= rows[0]["test_accuracy"] <= 1.0

def test_band_failure_exits_nonzero(parity, tmp_path, monkeypatch):
    monkeypatch.setenv("GENTUN_TPU_DATA", str(tmp_path))
    _write_archive(str(tmp_path), "mnist", separable=False)  # unlearnable
    out_md = str(tmp_path / "PARITY.md")
    rc = parity.main(TINY + ["--band", "1.1", "--out", out_md])
    assert rc == 1
    with open(out_md) as f:
        assert "| mnist | FAIL |" in f.read()


def test_synthetic_fallback_refused(parity, monkeypatch):
    """sklearn digits / synthetic fallbacks are NOT the paper's datasets."""
    monkeypatch.delenv("GENTUN_TPU_DATA", raising=False)
    assert parity.load_real("mnist", parity.ANCHORS["mnist"]) is None
