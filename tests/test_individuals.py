"""Individual tests: lazy fitness caching, reproduce semantics (SURVEY.md §2.3)."""

import numpy as np
import pytest

from gentun_tpu.genes import GenomeSpec, IntGene, genetic_cnn_genome
from gentun_tpu.individuals import GeneticCnnIndividual, Individual


class CountingIndividual(Individual):
    """Fitness = sum of gene bits; counts evaluations to prove caching."""

    eval_count = 0

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (3,))))

    def evaluate(self):
        type(self).eval_count += 1
        return float(sum(sum(g) for g in self.genes.values()))


@pytest.fixture(autouse=True)
def reset_counter():
    CountingIndividual.eval_count = 0


def make(genes=None, **kw):
    return CountingIndividual(x_train=np.zeros(1), y_train=np.zeros(1), genes=genes,
                              rng=np.random.default_rng(0), **kw)


def test_fitness_is_lazy_and_cached():
    ind = make()
    assert CountingIndividual.eval_count == 0
    f1 = ind.get_fitness()
    f2 = ind.get_fitness()
    assert f1 == f2
    assert CountingIndividual.eval_count == 1


def test_mutation_resets_fitness_only_on_change():
    ind = make(genes={"S_1": (1, 0, 1)})
    ind.get_fitness()
    ind.mutation_rate = 0.0
    ind.mutate()
    assert ind.fitness_evaluated  # no-op mutation keeps the cache
    ind.mutation_rate = 1.0
    ind.mutate()
    assert not ind.fitness_evaluated
    assert ind.get_fitness() == 1.0  # (0,1,0)
    assert CountingIndividual.eval_count == 2


def test_reproduce_child_is_unevaluated():
    a, b = make(), make()
    a.get_fitness(), b.get_fitness()
    child = a.reproduce(b)
    assert not child.fitness_evaluated
    assert child is not a and child is not b


def test_copy_preserves_cached_fitness_for_same_genes():
    ind = make()
    ind.get_fitness()
    clone = ind.copy()
    assert clone.fitness_evaluated  # elites don't retrain (SURVEY §2.3)
    clone2 = ind.copy(genes={"S_1": tuple(1 - b for b in ind.genes["S_1"])})
    assert not clone2.fitness_evaluated


def test_set_fitness_external():
    """Distributed master writes worker replies via set_fitness (SURVEY §3.2)."""
    ind = CountingIndividual(genes={"S_1": (0, 0, 0)}, rng=np.random.default_rng(0))
    ind.set_fitness(0.75)
    assert ind.get_fitness() == 0.75
    assert CountingIndividual.eval_count == 0


def test_missing_data_raises():
    ind = GeneticCnnIndividual(genes={"S_1": (0, 0, 0), "S_2": (0,) * 10},
                               rng=np.random.default_rng(0))
    with pytest.raises(RuntimeError):
        ind.get_fitness()


def test_extra_kwargs_fold_into_additional_parameters():
    ind = CountingIndividual(rng=np.random.default_rng(0), nodes=(3,), kfold=3)
    assert ind.additional_parameters["kfold"] == 3
    assert ind.spec.names == ["S_1"]


def test_crossover_uses_parent_rates():
    a = make(genes={"S_1": (0, 0, 0)})
    b = make(genes={"S_1": (1, 1, 1)})
    a.crossover_rate = 0.0
    child = a.crossover(b)
    assert child.genes == a.genes
