"""CI guards over the observability surface itself.

Three drift traps that previously only existed as eyeballs:

- the broker-throughput hot-path gate table, now embedded in the
  committed ``scripts/broker_throughput.json`` artifact — a gated plane
  creeping past 2% of dispatch fails HERE, not in a stderr table nobody
  re-reads;
- the registry ↔ ``docs/OBSERVABILITY.md`` metric-catalog agreement
  (``scripts/check_metric_docs.py``) — every library metric has a doc
  row, every doc row still names a live metric;
- the ``gentun_trace.py slo`` timeline reconstruction — fire→clear
  episode pairing, durations, evidence tails.
"""

import importlib.util
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# hot-path gate table (scripts/broker_throughput.py + committed artifact)
# ---------------------------------------------------------------------------


class TestHotPathGate:
    @pytest.fixture(scope="class")
    def artifact(self):
        path = os.path.join(REPO, "scripts", "broker_throughput.json")
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)

    def test_committed_artifact_has_the_table(self, artifact):
        table = artifact["hot_path_table"]
        assert table["gate_max_pct"] == 2.0
        gated = [r for r in table["rows"] if r["gated"]]
        assert len(gated) >= 9  # every gated control plane has a row
        assert table["within_gate"] is True

    def test_every_gated_plane_within_two_percent(self, artifact):
        over = [(r["plane"], r["overhead_pct"])
                for r in artifact["hot_path_table"]["rows"]
                if r["gated"] and r["overhead_pct"] > 2.0]
        assert not over, f"hot-path planes over the 2% gate: {over}"

    def test_builder_is_pure_and_consistent(self, artifact):
        bt = _load_script("broker_throughput")
        rebuilt = bt.hot_path_table(artifact)
        assert rebuilt == artifact["hot_path_table"]
        # Every plane held to the gate is represented in the constant.
        keys = {r.get("key") for r in rebuilt["rows"] if r["gated"]}
        assert keys == {k for k, _name in bt.HOT_PATH_GATED_PLANES}

    def test_builder_flags_a_regression(self, artifact):
        bt = _load_script("broker_throughput")
        bad = json.loads(json.dumps(artifact))  # deep copy
        bad["journal"]["overhead_pct"] = 3.7
        assert bt.hot_path_table(bad)["within_gate"] is False


# ---------------------------------------------------------------------------
# metric-catalog drift guard (scripts/check_metric_docs.py)
# ---------------------------------------------------------------------------


class TestMetricDocs:
    def test_repo_catalog_and_registry_agree(self):
        cmd = _load_script("check_metric_docs")
        result = cmd.check()
        assert not result["missing_from_docs"], (
            "registry metrics without a docs/OBSERVABILITY.md row: "
            f"{result['missing_from_docs']}")
        assert not result["stale_doc_rows"], (
            "doc rows for metrics that no longer exist: "
            f"{result['stale_doc_rows']}")
        assert result["ok"]

    def test_doc_row_parser(self, tmp_path):
        cmd = _load_script("check_metric_docs")
        doc = tmp_path / "OBS.md"
        doc.write_text(
            "| metric | type | labels | meaning |\n"
            "|---|---|---|---|\n"
            "| `jobs_total` | counter | — | jobs |\n"
            "| `depth` | gauge | `shard` | depth |\n"
            "| `not_a_metric` | fires when | page |\n"  # SLO-rule row shape
            "plain prose mentioning `other_name` |\n")
        rows = cmd.doc_metrics(str(doc))
        assert rows == {"jobs_total": "counter", "depth": "gauge"}

    def test_instrument_regex_matches_multiline_calls(self):
        cmd = _load_script("check_metric_docs")
        src = ('reg.counter("a_total", x=1).inc()\n'
               'reg.histogram(\n    "b_seconds").observe(1)\n'
               'reg.gauge(name_var).set(1)\n')  # variable: not collected
        assert cmd._INSTRUMENT_RE.findall(src) == ["a_total", "b_seconds"]


# ---------------------------------------------------------------------------
# gentun_trace slo subcommand
# ---------------------------------------------------------------------------


class TestSloTimeline:
    @pytest.fixture(scope="class")
    def trace_mod(self):
        return _load_script("gentun_trace")

    def _records(self):
        return [
            {"type": "alert", "event": "fire", "rule": "canary_correctness",
             "severity": "page", "subject": "fleet", "value": 1.0,
             "threshold": 0.0, "transition_seq": 1, "firing_since": 100.0,
             "t": 100.0},
            {"type": "scale", "action": "up", "rule": "canary_correctness",
             "subject": "fleet", "transition_seq": 1, "value": 1.0,
             "threshold": 0.0, "evidence": [[98.0, 0.0], [99.0, 0.0],
                                            [100.0, 1.0], [101.0, 1.0]],
             "from": 2, "to": 3, "outcome": "spawned 1", "t": 101.0},
            {"type": "event", "name": "canary_drift", "t_wall": 100.5,
             "data": {"genome": "g1"}},
            {"type": "alert", "event": "clear", "rule": "canary_correctness",
             "severity": "page", "subject": "fleet", "value": 0.0,
             "threshold": 0.0, "transition_seq": 2, "firing_since": 100.0,
             "t": 160.0},
            {"type": "alert", "event": "fire", "rule": "worker_idle_ratio",
             "severity": "warn", "subject": "w0", "value": 0.9,
             "threshold": 0.5, "transition_seq": 3, "firing_since": 200.0,
             "t": 200.0},
            {"type": "canary_probe", "cycle": 1, "result": "ok", "t": 90.0},
            {"type": "canary_probe", "cycle": 2, "result": "drift",
             "t": 100.5},
        ]

    def test_episodes_pair_fire_with_clear(self, trace_mod):
        tl = trace_mod.slo_timeline(self._records())
        assert tl["summary"] == {
            "fires": 2, "clears": 1, "open": 1,
            "by_severity": {"page": 1, "warn": 1},
            "scale_actions": 1,
            "canary_probes": {"drift": 1, "ok": 1},
            "canary_drift_events": 1,
        }
        ep = tl["episodes"][0]
        assert (ep["fire_seq"], ep["clear_seq"]) == (1, 2)
        assert ep["duration_s"] == 60.0 and not ep["open"]

    def test_window_gathers_actions_and_drifts(self, trace_mod):
        ep = trace_mod.slo_timeline(self._records())["episodes"][0]
        assert len(ep["actions"]) == 1
        act = ep["actions"][0]
        assert (act["from"], act["to"]) == (2, 3)
        assert act["evidence_tail"] == [[99.0, 0.0], [100.0, 1.0],
                                        [101.0, 1.0]]  # last 3 only
        assert ep["drifts"][0]["data"] == {"genome": "g1"}

    def test_open_episode_and_render(self, trace_mod):
        tl = trace_mod.slo_timeline(self._records())
        assert tl["episodes"][1]["open"] is True
        assert tl["episodes"][1]["duration_s"] is None
        text = trace_mod.render_slo(tl)
        assert "canary_correctness" in text and "(open)" in text

    def test_empty_ledger(self, trace_mod):
        tl = trace_mod.slo_timeline([])
        assert tl["episodes"] == [] and tl["summary"]["fires"] == 0
        assert "no alert transitions" in trace_mod.render_slo(tl)
