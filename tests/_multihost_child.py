"""Subprocess body for the multi-host tests (``test_multihost.py``).

Each invocation is one process of an N-process jax CPU cluster (8/N
virtual devices per process → always 8 global).  The parent test sets
JAX_PLATFORMS / XLA_FLAGS before spawning; this module initializes
``jax.distributed``,
then either runs the sharded population CV (``cv`` mode, leader writes the
accuracies to a JSON file for the parent to compare against its own
single-process run) or drives a full multi-host worker against the
parent's broker (``worker`` mode).
"""

import json
import sys

import numpy as np


def build_workload():
    """The tiny deterministic CV workload shared by child and parent."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    genomes = [
        {
            "S_1": tuple(int(b) for b in rng.integers(0, 2, 3)),
            "S_2": tuple(int(b) for b in rng.integers(0, 2, 6)),
            "S_3": tuple(int(b) for b in rng.integers(0, 2, 10)),
        }
        for _ in range(4)
    ]
    config = dict(
        nodes=(3, 4, 5),
        kernels_per_layer=(8, 8, 8),
        kfold=2,
        epochs=(1,),
        learning_rate=(0.05,),
        batch_size=16,
        dense_units=16,
        compute_dtype="float32",
        seed=0,
    )
    return x, y, genomes, config


def build_small_cnn_workload():
    """Single-stage Genetic-CNN workload for the worker-cnn e2e test.

    The full ``build_workload`` supergraph costs minutes of XLA compile on
    CPU *per process*; this one compiles in tens of seconds while still
    exercising the identical code path (GentunClient → Population.evaluate
    → sharded cross_validate_population over the global mesh).
    """
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    genomes = [{"S_1": tuple(int(b) for b in rng.integers(0, 2, 3))} for _ in range(4)]
    config = dict(
        nodes=(3,),
        kernels_per_layer=(6,),
        kfold=2,
        epochs=(1,),
        learning_rate=(0.05,),
        batch_size=16,
        dense_units=16,
        compute_dtype="float32",
        seed=0,
    )
    return x, y, genomes, config


def build_v5e32_workload():
    """Single-stage workload shaped for the v5e-32 mesh (8 pop × 4 data).

    8 genomes so the population axis fills all 8 mesh rows; single stage so
    8 concurrent CPU XLA compiles (one per cluster process) stay in tens of
    seconds, not minutes — the sharding/collective shapes are what the test
    exercises, not supergraph size.
    """
    rng = np.random.default_rng(5)
    x = rng.normal(size=(64, 8, 8, 3)).astype(np.float32)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    genomes = [{"S_1": tuple(int(b) for b in rng.integers(0, 2, 3))} for _ in range(8)]
    config = dict(
        nodes=(3,),
        kernels_per_layer=(6,),
        kfold=2,
        epochs=(1,),
        learning_rate=(0.05,),
        batch_size=16,
        dense_units=16,
        compute_dtype="float32",
        seed=0,
    )
    return x, y, genomes, config


def run_cv(mesh):
    from gentun_tpu.models.cnn import GeneticCnnModel

    x, y, genomes, config = build_workload()
    return GeneticCnnModel.cross_validate_population(x, y, genomes, mesh=mesh, **config)


def run_cv_v5e32(mesh):
    from gentun_tpu.models.cnn import GeneticCnnModel

    x, y, genomes, config = build_v5e32_workload()
    return GeneticCnnModel.cross_validate_population(x, y, genomes, mesh=mesh, **config)


class OneMax:
    """Placeholder so ``worker`` mode can import a cheap species lazily."""


def _one_max_species():
    from gentun_tpu import Individual, genetic_cnn_genome

    class _OneMax(Individual):
        def build_spec(self, **params):
            return genetic_cnn_genome((4, 4))

        def evaluate(self):
            return float(sum(sum(g) for g in self.genes.values()))

    return _OneMax


def main() -> None:
    mode, pid, nproc, coord_port, out_path = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        int(sys.argv[4]),
        sys.argv[5],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    from gentun_tpu.parallel import multihost

    multihost.initialize(f"127.0.0.1:{coord_port}", nproc, pid)
    assert jax.process_count() == nproc
    # 8 global devices for the classic modes; 32 for the v5e-32 shape.
    expect_devices = 32 if mode == "cv32" else 8
    assert jax.device_count() == expect_devices, jax.device_count()

    # Broadcast sanity on every run: the leader's object reaches all ranks
    # through the device fabric.
    obj = {"gen": 1, "payload": [1, 2, 3]} if multihost.is_leader() else None
    got = multihost.broadcast_payload(obj)
    assert got == {"gen": 1, "payload": [1, 2, 3]}, got

    if mode == "cv":
        from jax.sharding import NamedSharding, PartitionSpec as P

        from gentun_tpu.parallel.mesh import auto_mesh

        mesh = auto_mesh(devices=jax.devices(), pop_axis=2, data_axis=4)
        # ADVICE r3: re-placing a non-addressable global array under a
        # DIFFERENT sharding must raise place()'s descriptive error, not
        # numpy's obscure addressability failure.  Only reachable in a
        # real multi-process cluster, so it is pinned here.
        arr = multihost.place(
            np.arange(16.0, dtype=np.float32).reshape(16, 1),
            NamedSharding(mesh, P("pop", None)),
        )
        if not arr.is_fully_addressable:
            try:
                multihost.place(arr, NamedSharding(mesh, P("data", None)))
                raise AssertionError("expected ValueError for non-addressable re-place")
            except ValueError as e:
                assert "non-fully-addressable" in str(e), e
        accs = run_cv(mesh)
        if multihost.is_leader():
            with open(out_path, "w") as f:
                json.dump([float(a) for a in accs], f)
    elif mode == "cv32":
        # The v5e-32 (VERDICT r4 item 3): 32 global devices on an (8, 4)
        # pop×data mesh — 8 processes × 4 devices in the cluster run, or
        # 1 process × 32 devices for the reference run.
        from gentun_tpu.parallel.mesh import auto_mesh

        mesh = auto_mesh(devices=jax.devices(), pop_axis=8, data_axis=4)
        accs = run_cv_v5e32(mesh)
        if multihost.is_leader():
            with open(out_path, "w") as f:
                json.dump([float(a) for a in accs], f)
    elif mode in ("worker", "worker-cnn"):
        broker_port, max_jobs = int(sys.argv[6]), int(sys.argv[7])
        from gentun_tpu.distributed import GentunClient

        if mode == "worker-cnn":
            # The v5e-32 worker's EXACT composition (VERDICT r3 item 4):
            # broker jobs → leader broadcast → Population.evaluate →
            # sharded GeneticCnnModel CV across the process cluster.
            from gentun_tpu.individuals import GeneticCnnIndividual

            species = GeneticCnnIndividual
            x, y, _, _ = build_small_cnn_workload()
            data = (x, y)
            capacity = 4
        else:
            species = _one_max_species()
            data = (np.zeros(1, np.float32), np.zeros(1, np.float32))
            capacity = 2
        client = GentunClient(
            species,
            *data,
            host="127.0.0.1",
            port=broker_port,
            capacity=capacity,
            heartbeat_interval=0.2,
            reconnect_delay=0.1,
            multihost=True,
        )
        done = client.work(max_jobs=max_jobs if multihost.is_leader() else None)
        with open(out_path + f".rank{pid}", "w") as f:
            json.dump({"rank": pid, "jobs_done": done}, f)
    else:
        raise SystemExit(f"unknown mode {mode!r}")


if __name__ == "__main__":
    main()
