"""Live ops plane tests: health registry, stall watchdog, ops server,
flight recorder, and the stall→503→recovery acceptance path.

Covers the ops-plane acceptance criteria (docs/OBSERVABILITY.md "Live
ops plane"):

- heartbeat registry semantics: gating vs advisory sources, the
  enable-time re-stamp (no instant 503), disabled path is a no-op,
- StallWatchdog: ``max(floor, k × p95)`` threshold with the min-sample
  gate, flag/unflag discipline, counter + telemetry event + requeue
  callback on detection,
- OpsServer endpoints: /metrics is valid Prometheus text exposition,
  /healthz flips 200→503 on a stale gating source, /statusz carries
  heartbeats + providers, /debugz/flight serves the ring, 404 catalog,
- FlightRecorder: bounded ring, dump format, excepthook/SIGTERM dumpers,
- Prometheus label-value escaping round-trip (exposition spec),
- a killed run's truncated ``telemetry.jsonl`` stays line-parseable and
  the flight ring covers its tail,
- end-to-end: a 2-worker fleet with an injected worker stall flips
  /healthz to 503 within the watchdog window, the straggler is requeued
  to the healthy worker, and /healthz recovers to 200.
"""

import json
import signal
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from gentun_tpu import GeneticAlgorithm, Individual, genetic_cnn_genome
from gentun_tpu.telemetry import flight as flight_mod
from gentun_tpu.telemetry import health as health_mod
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.export import RunTelemetry
from gentun_tpu.telemetry.health import StallWatchdog
from gentun_tpu.telemetry.ops_server import (
    OpsServer,
    active_ops_server,
    start_ops_server,
    stop_ops_server,
)
from gentun_tpu.telemetry.registry import MetricsRegistry, get_registry


@pytest.fixture(autouse=True)
def _pristine_ops():
    """Ops state is process-global; every test starts and ends clean."""
    stop_ops_server()
    health_mod.reset()
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()
    yield
    stop_ops_server()
    health_mod.reset()
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    get_registry().reset()


class _ListSink:
    def __init__(self):
        self.records = []

    def record(self, rec):
        self.records.append(rec)


def _get(url, timeout=5.0):
    """(status, body bytes, content-type) — non-2xx handled, not raised."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read(), resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type", "")


# ---------------------------------------------------------------------------
# heartbeat registry + status providers
# ---------------------------------------------------------------------------


class TestHealthRegistry:
    def test_disabled_beat_is_noop(self):
        assert not health_mod.enabled()
        health_mod.beat("engine_loop")
        assert health_mod.heartbeats() == {}

    def test_beat_auto_registers_advisory(self):
        health_mod.enable()
        health_mod.beat("engine_loop")
        hb = health_mod.heartbeats()["engine_loop"]
        assert hb["timeout_s"] is None
        assert not hb["stale"]
        # advisory sources never gate, no matter how old
        ok, reasons = health_mod.check_health()
        assert ok and reasons == []

    def test_gating_source_goes_stale(self):
        health_mod.enable()
        health_mod.register_source("broker_loop", timeout=0.05)
        time.sleep(0.12)
        ok, reasons = health_mod.check_health()
        assert not ok
        assert any("broker_loop" in r and "stale" in r for r in reasons)
        # a beat heals it
        health_mod.beat("broker_loop")
        ok, reasons = health_mod.check_health()
        assert ok and reasons == []

    def test_enable_restamps_sources(self):
        """Ages accrued while the plane was off must not cause an instant
        503 on the first scrape after enabling."""
        health_mod.register_source("broker_loop", timeout=0.05)
        time.sleep(0.12)  # stale if the old stamp survived enable()
        health_mod.enable()
        ok, reasons = health_mod.check_health()
        assert ok, reasons

    def test_unregister_source(self):
        health_mod.enable()
        health_mod.register_source("x", timeout=0.01)
        health_mod.unregister_source("x")
        time.sleep(0.03)
        assert health_mod.check_health() == (True, [])

    def test_status_providers_lazy_and_error_isolated(self):
        calls = []

        def good():
            calls.append(1)
            return {"n": 7}

        def bad():
            raise RuntimeError("boom")

        health_mod.register_status_provider("engine", good)
        health_mod.register_status_provider("broken", bad)
        assert calls == []  # registration never calls
        snap = health_mod.status_snapshot()
        assert snap["engine"] == {"n": 7}
        assert "RuntimeError" in snap["broken"]["error"]

    def test_unregister_provider_identity_checked(self):
        fn_old = lambda: {"gen": 1}  # noqa: E731
        fn_new = lambda: {"gen": 2}  # noqa: E731
        health_mod.register_status_provider("engine", fn_old)
        health_mod.register_status_provider("engine", fn_new)  # last wins
        health_mod.unregister_status_provider("engine", fn_old)  # stale evict: no-op
        assert health_mod.status_snapshot()["engine"] == {"gen": 2}
        health_mod.unregister_status_provider("engine", fn_new)
        assert health_mod.status_snapshot() == {}


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


class TestStallWatchdog:
    def test_invalid_args(self):
        with pytest.raises(ValueError):
            StallWatchdog(floor_s=0)
        with pytest.raises(ValueError):
            StallWatchdog(k=-1)

    def test_threshold_floor_until_min_samples(self):
        wd = StallWatchdog(floor_s=2.0, k=4.0, min_samples=3)
        assert wd.threshold() == 2.0
        for i in range(3):  # instant round trips: p95 ≈ 0, floor still wins
            wd.job_started(f"j{i}", "w0")
            wd.job_finished(f"j{i}")
        assert wd.threshold() == 2.0

    def test_threshold_tracks_p95(self):
        wd = StallWatchdog(floor_s=0.001, k=2.0, min_samples=4)
        wd._rtts.extend([1.0, 1.0, 1.0, 10.0])  # p95 lands on the outlier
        assert wd.threshold() == pytest.approx(20.0)

    def test_flags_once_and_counts(self):
        wd = StallWatchdog(floor_s=1.0, k=4.0)
        wd.job_started("j1", "w0")
        future = time.monotonic() + 5.0
        newly = wd.check(now=future)
        assert [s["job_id"] for s in newly] == ["j1"]
        assert newly[0]["worker_id"] == "w0"
        assert wd.detected_total == 1
        assert wd.check(now=future + 1.0) == []  # flagged at most once
        assert wd.detected_total == 1
        snap = get_registry().snapshot()
        (c,) = [c for c in snap["counters"]
                if c["name"] == "stragglers_detected_total"]
        assert c["value"] == 1.0 and c["labels"] == {"worker": "w0"}

    def test_finish_clears_flag_and_samples_rtt(self):
        wd = StallWatchdog(floor_s=0.001, k=4.0)
        wd.job_started("j1", "w0")
        wd.check(now=time.monotonic() + 1.0)
        assert wd.stragglers()
        wd.job_finished("j1")
        assert wd.stragglers() == []
        assert wd.in_flight() == 0
        assert len(wd._rtts) == 1  # finish is a round trip

    def test_removed_takes_no_rtt_sample(self):
        wd = StallWatchdog(floor_s=1.0)
        wd.job_started("j1", "w0")
        wd.job_removed("j1")
        assert wd.in_flight() == 0
        assert len(wd._rtts) == 0  # a requeue is not a round trip

    def test_on_straggler_callback_and_event(self):
        spans_mod.enable()
        sink = _ListSink()
        spans_mod.set_run_sink(sink)
        hits = []
        wd = StallWatchdog(floor_s=0.5, on_straggler=hits.append)
        wd.job_started("j9", "w1")
        wd.check(now=time.monotonic() + 2.0)
        assert len(hits) == 1 and hits[0]["job_id"] == "j9"
        events = [r for r in sink.records if r.get("type") == "event"]
        assert [e["name"] for e in events] == ["straggler_detected"]
        assert events[0]["data"]["worker_id"] == "w1"

    def test_straggler_gates_check_health(self):
        health_mod.enable()
        wd = StallWatchdog(floor_s=0.02)
        health_mod.register_watchdog(wd)
        wd.job_started("j1", "w0")
        time.sleep(0.06)
        ok, reasons = health_mod.check_health()  # check_health sweeps itself
        assert not ok
        assert any("straggler" in r and "j1" in r for r in reasons)
        wd.job_finished("j1")
        assert health_mod.check_health() == (True, [])


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded(self, tmp_path):
        rec = flight_mod.FlightRecorder(capacity=4, path=str(tmp_path / "f.jsonl"))
        for i in range(10):
            rec.record({"type": "span", "kind": "k", "i": i})
        assert len(rec) == 4
        assert rec.total == 10
        assert [r["i"] for r in rec.snapshot()] == [6, 7, 8, 9]  # newest kept

    def test_dump_format(self, tmp_path):
        rec = flight_mod.FlightRecorder(capacity=8, path=str(tmp_path / "f.jsonl"))
        for i in range(12):
            rec.record({"type": "event", "name": "tick", "i": i})
        out = rec.dump(reason="unit")
        lines = [json.loads(l) for l in open(out, encoding="utf-8")]
        head = lines[0]
        assert head["type"] == "flight" and head["reason"] == "unit"
        assert head["capacity"] == 8
        assert head["recorded"] == 8 and head["dropped"] == 4
        assert len(lines) == 1 + 8
        assert lines[-1]["i"] == 11

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            flight_mod.FlightRecorder(capacity=0)

    def test_enable_routes_spans_through_ring(self, tmp_path):
        rec = flight_mod.enable(path=str(tmp_path / "f.jsonl"), capacity=16)
        try:
            assert spans_mod.enabled()  # flight arms span collection
            assert flight_mod.active() is rec
            with spans_mod.span("gen"):
                pass
            spans_mod.record_event("tick")
            kinds = {r.get("kind") or r.get("name") for r in rec.snapshot()}
            assert kinds == {"gen", "tick"}
        finally:
            flight_mod.disable()
        assert flight_mod.active() is None
        assert not spans_mod.enabled()  # no run sink held it open

    def test_disable_keeps_spans_for_run_sink(self, tmp_path):
        flight_mod.enable(path=str(tmp_path / "f.jsonl"))
        spans_mod.set_run_sink(_ListSink())
        flight_mod.disable()
        assert spans_mod.enabled()  # RunTelemetry still consuming

    def test_run_close_keeps_spans_for_flight(self, tmp_path):
        """The mirror case: closing a RunTelemetry artifact must not
        silence the flight recorder a live ops plane still holds."""
        rec = flight_mod.enable(path=str(tmp_path / "f.jsonl"))
        try:
            with RunTelemetry(str(tmp_path / "t.jsonl"), label="x"):
                pass
            assert spans_mod.enabled()  # flight ring still consuming
            before = rec.total
            spans_mod.record_event("after_run_close")
            assert rec.total == before + 1
        finally:
            flight_mod.disable()
        assert not spans_mod.enabled()

    def test_excepthook_dumps_then_chains(self, tmp_path):
        path = str(tmp_path / "f.jsonl")
        rec = flight_mod.enable(path=path)
        with spans_mod.span("doomed"):
            pass
        chained = []
        saved = flight_mod._prev_excepthook
        flight_mod._prev_excepthook = lambda *a: chained.append(a)
        try:
            flight_mod._excepthook(ValueError, ValueError("boom"), None)
        finally:
            flight_mod._prev_excepthook = saved
        assert len(chained) == 1  # original hook still ran
        lines = [json.loads(l) for l in open(path, encoding="utf-8")]
        assert lines[0]["reason"] == "unhandled_exception"
        names = [r.get("name") for r in lines[1:]]
        assert "unhandled_exception" in names  # the exception itself is in the ring
        kinds = [r.get("kind") for r in lines[1:]]
        assert "doomed" in kinds  # ...alongside the tail of the run
        (ev,) = [r for r in lines[1:] if r.get("name") == "unhandled_exception"]
        assert ev["data"] == {"exc_type": "ValueError", "exc": "boom"}
        assert len(rec) >= 2

    def test_sigterm_handler_dumps_then_chains(self, tmp_path):
        path = str(tmp_path / "f.jsonl")
        flight_mod.enable(path=path)
        spans_mod.record_event("last_words")
        chained = []
        saved = flight_mod._prev_sigterm
        flight_mod._prev_sigterm = lambda *a: chained.append(a)
        try:
            flight_mod._sigterm_handler(signal.SIGTERM, None)
        finally:
            flight_mod._prev_sigterm = saved
        assert chained == [(signal.SIGTERM, None)]
        lines = [json.loads(l) for l in open(path, encoding="utf-8")]
        assert lines[0]["reason"] == "sigterm"
        assert any(r.get("name") == "last_words" for r in lines[1:])

    def test_hooks_installed_once(self, tmp_path):
        flight_mod.enable(path=str(tmp_path / "a.jsonl"))
        hook_a = sys.excepthook
        flight_mod.enable(path=str(tmp_path / "b.jsonl"))
        assert sys.excepthook is hook_a  # no re-wrap, no chain-to-self


# ---------------------------------------------------------------------------
# prometheus escaping (exposition spec round-trip)
# ---------------------------------------------------------------------------


def _unescape_label_value(s):
    """Inverse of the exposition-format escaping: \\\\ → \\, \\" → ",
    \\n → newline — parsed char-by-char as a scraper would."""
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, "\\" + nxt))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


class TestPrometheusEscaping:
    @pytest.mark.parametrize("value", [
        'back\\slash', 'quo"te', 'new\nline', 'all\\"of\nthem\\n', 'plain',
        '\\', '"', '\n', 'trailing\\',
    ])
    def test_label_value_round_trips(self, value):
        reg = MetricsRegistry()
        reg.counter("escaped_total", path=value).inc()
        text = reg.render_prometheus()
        (line,) = [l for l in text.splitlines() if l.startswith("escaped_total{")]
        # the sample line itself must stay one line (newline escaped)...
        escaped = line[len('escaped_total{path="'):line.rindex('"')]
        assert "\n" not in escaped
        # ...and a spec-compliant parser must recover the original value
        assert _unescape_label_value(escaped) == value

    def test_multiple_labels_sorted_and_escaped(self):
        reg = MetricsRegistry()
        reg.gauge("g", b='x"y', a="p\\q").set(1)
        text = reg.render_prometheus()
        assert 'g{a="p\\\\q",b="x\\"y"} 1' in text


# ---------------------------------------------------------------------------
# ops server endpoints
# ---------------------------------------------------------------------------


class TestOpsServer:
    def test_metrics_endpoint(self):
        reg = MetricsRegistry()
        reg.counter("jobs_total", worker="w0").inc(3)
        srv = OpsServer(port=0, registry=reg).start()
        try:
            code, body, ctype = _get(srv.url + "/metrics")
            assert code == 200
            assert ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
            text = body.decode("utf-8")
            assert "# TYPE jobs_total counter" in text
            assert 'jobs_total{worker="w0"} 3' in text
        finally:
            srv.stop()

    def test_healthz_flips_and_recovers(self):
        health_mod.enable()
        health_mod.register_source("broker_loop", timeout=0.05)
        srv = OpsServer(port=0).start()
        try:
            code, body, _ = _get(srv.url + "/healthz")
            assert code == 200
            assert json.loads(body)["status"] == "ok"
            time.sleep(0.12)  # let the gating source go stale
            code, body, _ = _get(srv.url + "/healthz")
            assert code == 503
            payload = json.loads(body)
            assert payload["status"] == "unhealthy"
            assert any("broker_loop" in r for r in payload["reasons"])
            health_mod.beat("broker_loop")  # self-heal
            code, _, _ = _get(srv.url + "/healthz")
            assert code == 200
        finally:
            srv.stop()

    def test_statusz_carries_heartbeats_and_providers(self):
        health_mod.enable()
        health_mod.register_source("broker_loop", timeout=10.0)
        health_mod.register_status_provider("engine", lambda: {"generation": 3})
        srv = OpsServer(port=0).start()
        try:
            code, body, ctype = _get(srv.url + "/statusz")
            assert code == 200 and ctype.startswith("application/json")
            snap = json.loads(body)
            assert snap["healthy"] is True
            assert snap["pid"] > 0 and snap["uptime_s"] >= 0
            assert snap["heartbeats"]["broker_loop"]["timeout_s"] == 10.0
            assert snap["engine"] == {"generation": 3}
        finally:
            srv.stop()

    def test_debugz_flight_404_without_recorder(self):
        srv = OpsServer(port=0).start()
        try:
            code, body, _ = _get(srv.url + "/debugz/flight")
            assert code == 404
            assert "no flight recorder" in json.loads(body)["error"]
        finally:
            srv.stop()

    def test_unknown_path_lists_endpoints(self):
        srv = OpsServer(port=0).start()
        try:
            code, body, _ = _get(srv.url + "/nope")
            assert code == 404
            assert "/healthz" in json.loads(body)["endpoints"]
        finally:
            srv.stop()

    def test_start_stop_lifecycle(self, tmp_path):
        assert active_ops_server() is None
        assert not health_mod.enabled() and not spans_mod.enabled()
        srv = start_ops_server(port=0, flight_path=str(tmp_path / "f.jsonl"))
        assert active_ops_server() is srv
        assert health_mod.enabled()  # beats flow
        assert spans_mod.enabled()  # flight recorder armed
        assert flight_mod.active() is not None
        with spans_mod.span("probe"):
            pass
        code, body, ctype = _get(srv.url + "/debugz/flight")
        assert code == 200 and "ndjson" in ctype
        lines = [json.loads(l) for l in body.decode("utf-8").splitlines()]
        assert lines[0]["type"] == "flight" and lines[0]["reason"] == "debugz"
        assert any(r.get("kind") == "probe" for r in lines[1:])
        stop_ops_server()
        assert active_ops_server() is None
        assert not health_mod.enabled()
        assert not spans_mod.enabled()  # ops plane was the only consumer
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(srv.url + "/healthz", timeout=0.5)

    def test_ops_plane_off_by_default(self):
        """A process that never opts in runs the untouched disabled paths
        (the bit-identity guarantee rides on this)."""
        assert active_ops_server() is None
        assert flight_mod.active() is None
        assert not health_mod.enabled()
        assert not spans_mod.enabled()


# ---------------------------------------------------------------------------
# killed-run artifact: truncated telemetry.jsonl + flight tail
# ---------------------------------------------------------------------------


class TestKilledRunArtifacts:
    def test_truncated_jsonl_parseable_and_flight_covers_tail(self, tmp_path):
        """A SIGKILLed master never writes the summary line.  Because the
        exporter flushes per record, the artifact must still be
        line-parseable as-is — and the flight ring holds the same tail
        for the crash dump."""
        tele_path = tmp_path / "telemetry.jsonl"
        flight_path = tmp_path / "flight.jsonl"
        rec = flight_mod.enable(path=str(flight_path), capacity=64)
        run = RunTelemetry(str(tele_path), label="doomed").install()
        try:
            for i in range(5):
                with spans_mod.span("generation", {"generation": i}):
                    pass
            spans_mod.record_event("checkpoint", {"generation": 4})
            # Simulate the kill: the file handle dies with the process —
            # no close(), no summary line.
            with run._lock:
                run._fh.close()
                run._fh = None
        finally:
            spans_mod.set_run_sink(None)
            flight_mod.disable()

        lines = [json.loads(l) for l in tele_path.read_text().splitlines()]
        assert lines[0]["type"] == "run_start"
        assert lines[-1]["type"] != "summary"  # truncated, by construction
        gens = [r for r in lines if r.get("kind") == "generation"]
        assert len(gens) == 5  # every pre-kill record is intact
        assert any(r.get("name") == "checkpoint" for r in lines)

        # the flight ring saw the same records; its dump reconstructs the tail
        out = rec.dump(reason="postmortem")
        flines = [json.loads(l) for l in open(out, encoding="utf-8")]
        fl_gens = [r for r in flines[1:] if r.get("kind") == "generation"]
        assert [r["attrs"]["generation"] for r in fl_gens] == [0, 1, 2, 3, 4]
        assert any(r.get("name") == "checkpoint" for r in flines[1:])


# ---------------------------------------------------------------------------
# end-to-end: 2-worker fleet, injected stall → 503 → requeue → recovery
# ---------------------------------------------------------------------------


class OneMax(Individual):
    """Cheap deterministic fitness: count of set bits."""

    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


def _spawn_workers(port, injector=None):
    """Two in-process workers; w0 optionally fault-injected."""
    from gentun_tpu.distributed import GentunClient

    stops = []
    for i, inj in enumerate([injector, None]):
        stop = threading.Event()
        threading.Thread(
            target=lambda s=stop, wid=f"w{i}", fi=inj: GentunClient(
                OneMax, *DATA, host="127.0.0.1", port=port,
                heartbeat_interval=0.2, reconnect_delay=0.1,
                worker_id=wid, fault_injector=fi,
            ).work(stop_event=s),
            daemon=True,
        ).start()
        stops.append(stop)
    return stops


def _hang_injector(duration):
    """w0 stalls its second eval batch; the hang also suppresses its
    heartbeats, but the fleet tests pin heartbeat_timeout=30 so the
    reaper stays out of the story — only the watchdog may act."""
    from gentun_tpu.distributed import FaultInjector, FaultPlan, FaultSpec

    return FaultInjector(FaultPlan([
        FaultSpec(hook="worker_pre_eval", kind="hang", at=1, duration=duration),
    ], seed=2026))


class TestEndToEndOps:
    def test_stall_flips_healthz_then_recovers(self, tmp_path):
        """Acceptance: /healthz 200 on a healthy 2-worker fleet, 503
        within the watchdog window after an injected worker stall, and
        back to 200 once the stalled job finally lands (no requeue —
        the flag persists for the whole hang, so the poller reliably
        observes both transitions)."""
        from gentun_tpu.distributed import DistributedPopulation

        srv = start_ops_server(port=0, flight_path=str(tmp_path / "flight.jsonl"))
        codes, statusz_mid = [], {}
        stop_poll = threading.Event()

        def _poll():
            while not stop_poll.is_set():
                codes.append(_get(srv.url + "/healthz")[0])
                snap = json.loads(_get(srv.url + "/statusz")[1])
                if "engine" in snap and "fleet" in snap:
                    statusz_mid.update(snap)  # keep a mid-run fleet view
                time.sleep(0.05)

        with DistributedPopulation(
            OneMax, size=8, seed=6, port=0, heartbeat_timeout=30.0,
            straggler_floor_s=0.75, straggler_k=4.0,
        ) as pop:
            _, port = pop.broker_address
            stops = _spawn_workers(port, injector=_hang_injector(3.0))
            poller = threading.Thread(target=_poll, daemon=True)
            poller.start()
            try:
                ga = GeneticAlgorithm(pop, seed=6)
                best = ga.run(2)
                assert best.get_fitness() > 0
                # fleet quiescent again: healthz must have healed
                final_code, final_body, _ = _get(srv.url + "/healthz")
            finally:
                stop_poll.set()
                poller.join(timeout=5.0)
                for s in stops:
                    s.set()

            # -- the stall surfaced, then healed -------------------------
            assert 503 in codes, f"stall never flipped healthz: {codes}"
            assert final_code == 200, json.loads(final_body)

            # -- watchdog counted the hung worker's job -------------------
            snap = get_registry().snapshot()
            dets = [c for c in snap["counters"]
                    if c["name"] == "stragglers_detected_total"]
            assert sum(c["value"] for c in dets) >= 1
            assert {c["labels"]["worker"] for c in dets} == {"w0"}

            # -- mid-run statusz carried both providers -------------------
            assert statusz_mid, "poller never saw a mid-run statusz"
            assert statusz_mid["engine"]["mode"] == "generational"
            assert statusz_mid["engine"]["trace_id"]  # live run span id
            fleet = statusz_mid["fleet"]
            assert fleet["straggler_requeue"] is False
            assert {w["worker_id"] for w in fleet["workers"]} <= {"w0", "w1"}

            # -- /metrics is scrape-ready exposition text -----------------
            code, body, ctype = _get(srv.url + "/metrics")
            assert code == 200 and "version=0.0.4" in ctype
            text = body.decode("utf-8")
            assert 'stragglers_detected_total{worker="w0"}' in text
            for line in text.splitlines():
                assert line.startswith("#") or " " in line  # name value pairs

            # -- the straggler left a telemetry event in the flight ring --
            code, body, _ = _get(srv.url + "/debugz/flight")
            assert code == 200
            flines = [json.loads(l) for l in body.decode("utf-8").splitlines()]
            assert "straggler_detected" in {r.get("name") for r in flines[1:]}

    def test_straggler_requeued_to_healthy_worker(self):
        """Opt-in requeue: the flagged job is pulled from the hung worker,
        redispatched, the counters/events record it, and the search
        completes with zero leaked broker state."""
        from gentun_tpu.distributed import DistributedPopulation

        health_mod.enable()
        spans_mod.enable()
        sink = _ListSink()
        spans_mod.set_run_sink(sink)
        with DistributedPopulation(
            OneMax, size=8, seed=6, port=0, heartbeat_timeout=30.0,
            straggler_floor_s=0.5, straggler_k=4.0, straggler_requeue=True,
        ) as pop:
            _, port = pop.broker_address
            stops = _spawn_workers(port, injector=_hang_injector(2.5))
            try:
                ga = GeneticAlgorithm(pop, seed=6)
                best = ga.run(2)
            finally:
                for s in stops:
                    s.set()
            assert best.get_fitness() > 0
            leaked = pop.broker.outstanding()
            assert all(v == 0 for v in leaked.values()), leaked

        snap = get_registry().snapshot()
        by_name = {}
        for c in snap["counters"]:
            by_name.setdefault(c["name"], []).append(c)
        assert sum(c["value"] for c in by_name["stragglers_detected_total"]) >= 1
        assert sum(c["value"] for c in by_name["stragglers_requeued_total"]) >= 1
        (req,) = by_name["stragglers_requeued_total"]
        assert req["labels"] == {"worker": "w0"}  # pulled from the hung worker

        names = [r["name"] for r in sink.records if r.get("type") == "event"]
        assert "straggler_detected" in names
        assert "straggler_requeued" in names
