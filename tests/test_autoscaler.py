"""SLO-driven autoscaler daemon + preemption-tolerant placement.

Covers the closed control loop the autoscaler PR builds: the ``/alertz``
edge-trigger fields (``transition_seq``/``firing_since`` — a poller must
see a fire→clear→fire cycle that lands entirely between two polls), the
scheduler/broker placement plane (rung-0 probes to preemptible members,
promotions pinned to stable, homogeneous fallback, off-path identity),
the ``preemptible`` wire field's conservative degradation, the
autoscaler-style drain race (prefetched-unstarted jobs all handed back,
zero lost), the :class:`LocalProcessBackend` process pool, and the
daemon's decision logic (hysteresis borrowed from the SLO machine,
cooldown, clamps, edge detection, decision records).
"""

import json
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from gentun_tpu import Individual, Population, genetic_cnn_genome
from gentun_tpu.distributed import GentunClient, JobBroker
from gentun_tpu.distributed.autoscaler import (
    AutoscalerDaemon,
    FleetBackend,
    LocalProcessBackend,
)
from gentun_tpu.distributed.sessions import FairShareScheduler
from gentun_tpu.telemetry import lineage
from gentun_tpu.telemetry import spans as spans_mod
from gentun_tpu.telemetry.aggregator import MetricsAggregator
from gentun_tpu.telemetry.registry import get_registry
from gentun_tpu.telemetry.slo import SeriesPoints, SloEngine, SloRule
from gentun_tpu.utils import fidelity_fingerprint


class OneMax(Individual):
    def build_spec(self, **params):
        return genetic_cnn_genome(tuple(params.get("nodes", (4, 4))))

    def evaluate(self):
        return float(sum(sum(g) for g in self.genes.values()))


class SlowOneMax(OneMax):
    def evaluate(self):
        time.sleep(0.5)
        return super().evaluate()


DATA = (np.zeros(1, np.float32), np.zeros(1, np.float32))


@pytest.fixture(autouse=True)
def _pristine_telemetry():
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    lineage.disable()
    lineage.reset_ledger()
    get_registry().reset()
    yield
    spans_mod.disable()
    spans_mod.set_run_sink(None)
    lineage.disable()
    lineage.reset_ledger()
    get_registry().reset()


class _ListSink:
    def __init__(self):
        self.records = []

    def record(self, rec):
        self.records.append(rec)


def _wait(predicate, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _spawn_worker(species, port, worker_id, capacity=1, prefetch_depth=None,
                  preemptible=False):
    stop = threading.Event()
    client = GentunClient(
        species, *DATA, host="127.0.0.1", port=port, capacity=capacity,
        prefetch_depth=prefetch_depth, worker_id=worker_id,
        heartbeat_interval=0.2, reconnect_delay=0.05, preemptible=preemptible,
    )
    t = threading.Thread(target=lambda: client.work(stop_event=stop), daemon=True)
    t.start()
    return client, stop, t


# ---------------------------------------------------------------------------
# /alertz edge triggering: transition_seq + firing_since (satellite 1)
# ---------------------------------------------------------------------------


def _mk_view(points_by_name):
    def view(pattern, **_):
        from gentun_tpu.telemetry.slo import match_series
        return [SeriesPoints(name, {"instance": "w0", "role": "worker"}, pts)
                for name, pts in points_by_name.items()
                if match_series(pattern, name)]
    return view


def _growing(now):
    return {"errors_total": [(now - 30, 0.0), (now, 3.0)]}


def _flat(now):
    return {"errors_total": [(now - 5, 3.0), (now, 3.0)]}


class TestAlertEdgeFields:
    RULE = SloRule(name="r", kind="increase", series="errors_total",
                   threshold=0.0, op=">", window_s=60.0, for_s=0.0,
                   clear_for_s=10.0, subject="fleet")

    def _alert(self, eng):
        return eng.snapshot()["alerts"][0]

    def test_polling_observes_fire_clear_fire_cycle(self):
        """A watcher that only polls ``snapshot()`` between transitions
        must still see every edge: the monotonic seq moves on each one,
        so fire→clear→fire reads as seq+2 even if both edges landed
        inside one poll gap."""
        eng = SloEngine([self.RULE])
        t0 = 1000.0
        assert eng.evaluate(_mk_view(_growing(t0)), now=t0)
        first = self._alert(eng)
        assert first["state"] == "firing"
        assert first["transition_seq"] == 1
        assert first["firing_since"] == t0
        # clear (healthy past clear_for_s) ...
        eng.evaluate(_mk_view(_flat(t0 + 50)), now=t0 + 50)
        eng.evaluate(_mk_view(_flat(t0 + 65)), now=t0 + 65)
        cleared = self._alert(eng)
        assert cleared["state"] == "inactive"
        assert cleared["transition_seq"] == 2
        assert cleared["firing_since"] == 0.0
        # ... and re-fire: a FRESH edge with a fresh seq and timestamp.
        assert eng.evaluate(_mk_view(_growing(t0 + 100)), now=t0 + 100)
        second = self._alert(eng)
        assert second["state"] == "firing"
        assert second["transition_seq"] == 3
        assert second["firing_since"] == t0 + 100
        # The cursor contract: seq strictly increased across the cycle.
        assert (first["transition_seq"] < cleared["transition_seq"]
                < second["transition_seq"])

    def test_seq_is_engine_global_across_rules(self):
        other = SloRule(name="r2", kind="increase", series="boom_total",
                        threshold=0.0, op=">", window_s=60.0, for_s=0.0,
                        clear_for_s=10.0, subject="fleet")
        eng = SloEngine([self.RULE, other])
        t0 = 1000.0
        view = _mk_view({**_growing(t0),
                         "boom_total": [(t0 - 30, 0.0), (t0, 1.0)]})
        fired = eng.evaluate(view, now=t0)
        assert sorted(t["transition_seq"] for t in fired) == [1, 2]

    def test_transition_records_carry_edge_fields(self):
        eng = SloEngine([self.RULE])
        t0 = 1000.0
        (rec,) = eng.evaluate(_mk_view(_growing(t0)), now=t0)
        assert rec["transition_seq"] == 1 and rec["firing_since"] == t0
        assert eng.snapshot()["history"][-1]["transition_seq"] == 1

    def test_aggregator_alert_record_carries_edge_fields(self):
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        rule = SloRule(name="deg", kind="increase", series="*_degraded_total",
                       threshold=0.0, op=">", window_s=60.0, for_s=0.0,
                       clear_for_s=3600.0, subject="instance")
        agg = MetricsAggregator("127.0.0.1", 0, slo_rules=[rule])
        for seq, v in ((1, 0.0), (2, 1.0)):
            ok, detail = agg.push({
                "instance": "w0", "role": "worker", "boot_id": "b", "seq": seq,
                "metrics": {"counters": [{
                    "name": "fitness_service_degraded_total",
                    "labels": {}, "value": v}], "gauges": [], "histograms": []},
            })
            assert ok, detail
            time.sleep(0.05)
        assert agg.evaluate_slos()
        recs = [r for r in sink.records if r.get("type") == "alert"]
        assert recs and recs[0]["transition_seq"] == 1
        assert recs[0]["firing_since"] > 0.0


# ---------------------------------------------------------------------------
# Scheduler placement filter
# ---------------------------------------------------------------------------


class TestSchedulerPlacement:
    @staticmethod
    def _sched():
        return FairShareScheduler(lambda sid: 1.0)

    def test_unplaceable_head_stays_queued_and_turn_passes(self):
        sched = self._sched()
        sched.push("a", "a0")
        sched.push("b", "b0")
        # a0 is pinned elsewhere: session a sits out, b serves — and a's
        # queue is untouched for the next (other-class) pass.
        got = sched.pop_next(lambda s: True, lambda j: True,
                             placeable=lambda j: j != "a0")
        assert got == ("b", "b0")
        assert sched.session_depth("a") == 1
        assert sched.pop_next(lambda s: True, lambda j: True,
                              placeable=lambda j: True) == ("a", "a0")

    def test_all_heads_blocked_returns_none_queue_intact(self):
        sched = self._sched()
        sched.push("a", "a0")
        sched.push("a", "a1")
        assert sched.pop_next(lambda s: True, lambda j: True,
                              placeable=lambda j: False) is None
        assert sched.depth() == 2
        # Intra-session FIFO preserved after the blocked pass.
        assert sched.pop_next(lambda s: True, lambda j: True) == ("a", "a0")
        assert sched.pop_next(lambda s: True, lambda j: True) == ("a", "a1")

    def test_invalid_head_still_discarded_under_placement(self):
        sched = self._sched()
        sched.push("a", "dead")
        sched.push("a", "live")
        assert sched.pop_next(lambda s: True, lambda j: j != "dead",
                              placeable=lambda j: True) == ("a", "live")
        assert sched.depth() == 0

    def test_blocked_session_charged_no_deficit(self):
        sched = self._sched()
        sched.push("a", "a0")
        sched.pop_next(lambda s: True, lambda j: True,
                       placeable=lambda j: False)
        # The blocked pass must not have consumed a's dispatch turn: with
        # a fresh competitor, a still wins its fair share immediately.
        sched.push("b", "b0")
        got = {sched.pop_next(lambda s: True, lambda j: True)
               for _ in range(2)}
        assert got == {("a", "a0"), ("b", "b0")}


# ---------------------------------------------------------------------------
# Preemptible wire field + placement-aware dispatch
# ---------------------------------------------------------------------------


def _tagged_jobs(prefix, genomes, rung):
    params = {"kfold": 2}
    fp = fidelity_fingerprint(params)
    return {
        f"{prefix}{i}": {
            "genes": g, "additional_parameters": params,
            "fidelity": {"v": 1, "rung": rung, "fingerprint": fp},
        } for i, g in enumerate(genomes)
    }


class TestPreemptibleWire:
    def test_hello_flag_lands_in_fleet_state(self):
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(OneMax, port, "pw-0", preemptible=True)
            c1, s1, _ = _spawn_worker(OneMax, port, "pw-1")
            assert _wait(lambda: broker.fleet_members() == 2)
            assert broker.fleet_preemptible() == 1
            ops = broker._ops_status()
            assert ops["preemptible_members"] == 1
            by_id = {w["worker_id"]: w for w in ops["workers"]}
            assert by_id["pw-0"]["preemptible"] is True
            # Back-compat: a worker that never sent the field is stable.
            assert by_id["pw-1"]["preemptible"] is False
            s0.set(), s1.set()
        finally:
            broker.stop()

    def test_advertise_updates_placement_class(self):
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(OneMax, port, "adv-0")
            assert _wait(lambda: broker.fleet_members() == 1)
            assert broker.fleet_preemptible() == 0
            c0.preemptible = True  # spot VM demoted mid-run
            c0.advertise()
            assert _wait(lambda: broker.fleet_preemptible() == 1)
            s0.set()
        finally:
            broker.stop()

    def test_drain_reason_preempt_attributed_in_lineage(self):
        """A --preempt self-drain's requeued jobs must be attributable:
        the lineage ledger separates preemption churn from operator
        drains."""
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        lineage.enable()
        genomes = [ind.get_genes() for ind in
                   Population(OneMax, DATA, size=4, seed=3, maximize=True)]
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(SlowOneMax, port, "pre-0", capacity=1,
                                      prefetch_depth=3, preemptible=True)
            assert _wait(lambda: broker.fleet_members() == 1)
            broker.submit({f"j{i}": {"genes": g}
                           for i, g in enumerate(genomes)})
            assert _wait(
                lambda: broker._ops_status()["jobs_in_flight"] == 4)
            c0.drain(reason="preempt")  # the SIGUSR1 deadline path
            reqs = lambda: [r for r in sink.records
                            if r.get("type") == "lineage"
                            and r.get("event") == "requeued"]
            assert _wait(lambda: len(reqs()) == 3, timeout=15)
            assert all(r["reason"] == "preempt" for r in reqs())
            s0.set()
            c1, s1, _ = _spawn_worker(OneMax, port, "pre-1")
            results = broker.gather([f"j{i}" for i in range(4)], timeout=30)
            assert len(results) == 4
            assert all(v == 0 for v in broker.outstanding().values())
            s1.set()
        finally:
            broker.stop()


class TestPlacementDispatch:
    def test_mixed_fleet_routes_rungs_by_class(self):
        """The acceptance routing: in a mixed fleet, EVERY rung-0 probe
        dispatches to the preemptible member and EVERY rung-1 promotion
        pins to stable — verified from lineage attribution alone."""
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        lineage.enable()
        genomes = [ind.get_genes() for ind in
                   Population(OneMax, DATA, size=8, seed=5, maximize=True)]
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(OneMax, port, "place-pre", capacity=1,
                                      prefetch_depth=2, preemptible=True)
            c1, s1, _ = _spawn_worker(OneMax, port, "place-stable", capacity=1,
                                      prefetch_depth=2)
            assert _wait(lambda: broker.fleet_members() == 2)
            jobs = {**_tagged_jobs("probe", genomes[:4], rung=0),
                    **_tagged_jobs("promo", genomes[4:], rung=1)}
            broker.submit(jobs)
            results = broker.gather(list(jobs), timeout=30)
            assert len(results) == 8
            dispatched = [r for r in sink.records
                          if r.get("type") == "lineage"
                          and r.get("event") == "dispatched"]
            by_job = {r["job"]: r for r in dispatched}
            assert len(by_job) == 8
            for jid, rec in by_job.items():
                if jid.startswith("probe"):
                    assert rec["worker"] == "place-pre", (jid, rec)
                    assert rec["rung"] == 0
                else:
                    assert rec["worker"] == "place-stable", (jid, rec)
                    assert rec["rung"] == 1
            assert all(v == 0 for v in broker.outstanding().values())
            s0.set(), s1.set()
        finally:
            broker.stop()

    def test_homogeneous_preemptible_fleet_takes_all_classes(self):
        """Fallback: when a class has no capacity, placement disengages —
        a preemptible-only fleet still evaluates rung-1 promotions."""
        genomes = [ind.get_genes() for ind in
                   Population(OneMax, DATA, size=4, seed=9, maximize=True)]
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(OneMax, port, "homo-0",
                                      preemptible=True)
            assert _wait(lambda: broker.fleet_members() == 1)
            jobs = _tagged_jobs("promo", genomes, rung=1)
            broker.submit(jobs)
            results = broker.gather(list(jobs), timeout=30)
            assert len(results) == 4
            assert all(v == 0 for v in broker.outstanding().values())
            s0.set()
        finally:
            broker.stop()

    def test_stable_only_dispatch_bit_identical_to_pre_placement(self):
        """PR-2 off-path contract: with no preemptible member, placement
        never engages — dispatch order (lineage-attributed) is exactly
        the scheduler's FIFO, as before the placement plane existed."""
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        lineage.enable()
        genomes = [ind.get_genes() for ind in
                   Population(OneMax, DATA, size=4, seed=2, maximize=True)]
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(OneMax, port, "off-0", capacity=1,
                                      prefetch_depth=0)
            assert _wait(lambda: broker.fleet_members() == 1)
            jobs = {**_tagged_jobs("p", genomes[:2], rung=0),
                    **_tagged_jobs("q", genomes[2:], rung=1)}
            broker.submit(jobs)
            results = broker.gather(list(jobs), timeout=30)
            assert len(results) == 4
            order = [r["job"] for r in sink.records
                     if r.get("type") == "lineage"
                     and r.get("event") == "dispatched"]
            assert order == list(jobs), order  # submit order == FIFO
            s0.set()
        finally:
            broker.stop()


# ---------------------------------------------------------------------------
# Drain race (satellite 2): autoscaler-style drain with prefetched jobs
# ---------------------------------------------------------------------------


class TestAutoscalerDrainRace:
    def test_drain_hands_back_every_prefetched_unstarted_job(self):
        """The exact race a scale-down decision creates: SIGTERM lands
        while the worker's local prefetch queue holds unstarted jobs.
        Every one must come back through ``drain {requeue: [...]}`` —
        zero lost, broker quiescent after a replacement finishes."""
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        lineage.enable()
        genomes = [ind.get_genes() for ind in
                   Population(OneMax, DATA, size=5, seed=17, maximize=True)]
        expected = {f"d{i}": float(sum(sum(g) for g in genomes[i].values()))
                    for i in range(5)}
        broker = JobBroker(port=0).start()
        try:
            _, port = broker.address
            c0, s0, _ = _spawn_worker(SlowOneMax, port, "race-0", capacity=1,
                                      prefetch_depth=4)
            assert _wait(lambda: broker.fleet_members() == 1)
            broker.submit({f"d{i}": {"genes": genomes[i]} for i in range(5)})
            # The full window (1 training + 4 prefetched-unstarted) is out.
            assert _wait(lambda: broker._ops_status()["jobs_in_flight"] == 5)
            c0.drain()  # what LocalProcessBackend's SIGTERM triggers
            reqs = lambda: [r for r in sink.records
                            if r.get("type") == "lineage"
                            and r.get("event") == "requeued"
                            and r.get("reason") == "drain"]
            # All 4 unstarted jobs hand back via the drain frame — not the
            # disconnect sweep, which would tag them the same but race the
            # worker's exit.
            assert _wait(lambda: len(reqs()) == 4, timeout=15)
            assert {r["job"] for r in reqs()} == {f"d{i}" for i in range(1, 5)}
            # Zero lost: a replacement drains the conserved backlog dry.
            s0.set()
            c1, s1, _ = _spawn_worker(OneMax, port, "race-1", capacity=1,
                                      prefetch_depth=4)
            results = broker.gather(list(expected), timeout=30)
            assert results == expected
            assert all(v == 0 for v in broker.outstanding().values()), \
                broker.outstanding()
            s1.set()
        finally:
            broker.stop()


# ---------------------------------------------------------------------------
# LocalProcessBackend
# ---------------------------------------------------------------------------


_SLEEPER = ("import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(0))\n"
            "time.sleep(600)\n")


class TestLocalProcessBackend:
    def test_spawn_drain_reap_cycle(self):
        be = LocalProcessBackend([sys.executable, "-c", _SLEEPER])
        try:
            assert be.size() == 0
            assert be.spawn(2) == 2
            assert be.size() == 2
            assert be.drain(1) == 1  # SIGTERM, the worker drain signal
            assert _wait(lambda: (be.reap(), be.size() == 1)[1], timeout=10)
            assert be.drain(5) == 1  # clamped to the living members
            assert _wait(lambda: (be.reap(), be.size() == 0)[1], timeout=10)
            desc = be.describe()
            assert desc["spawned_total"] == 2 and desc["reaped_total"] == 2
        finally:
            be.drain(be.size())

    def test_empty_argv_refused(self):
        with pytest.raises(ValueError):
            LocalProcessBackend([])


# ---------------------------------------------------------------------------
# AutoscalerDaemon decisions
# ---------------------------------------------------------------------------


class _FakeAgg:
    """Duck-typed alert source: exactly the two reads the daemon does."""

    def __init__(self):
        self.alerts = []
        self.rules = [{"name": "queue_depth_growth",
                       "series": "session_queue_depth"},
                      {"name": "worker_idle_ratio",
                       "series": "worker_idle_s_sum"}]

    def alertz(self):
        return {"active": [a for a in self.alerts
                           if a["state"] == "firing"],
                "alerts": list(self.alerts), "history": [],
                "rules": self.rules}

    def ringz(self, name="*", instance=None):
        return {"series": [{"name": name, "labels": {},
                            "points": [[1.0, 2.0], [2.0, 9.0]]}],
                "ring_len": 128}

    def fire(self, rule, seq, subject="fleet", value=12.0):
        self.alerts = [a for a in self.alerts if a["rule"] != rule]
        self.alerts.append({
            "rule": rule, "subject": subject, "state": "firing",
            "value": value, "threshold": 8.0, "severity": "page",
            "transition_seq": seq, "firing_since": 100.0 + seq,
        })

    def clear(self, rule):
        self.alerts = [a for a in self.alerts if a["rule"] != rule]


class _FakeBackend(FleetBackend):
    def __init__(self, size=1):
        self._size = size
        self.spawned = 0
        self.drained = 0

    def size(self):
        return self._size

    def spawn(self, n):
        self._size += n
        self.spawned += n
        return n

    def drain(self, n):
        self._size -= n
        self.drained += n
        return n

    def reap(self):
        return 0


def _daemon(be, agg, **kw):
    kw.setdefault("serve_http", False)
    kw.setdefault("cooldown_s", 0.0)
    return AutoscalerDaemon(be, aggregator=agg, **kw)


class TestAutoscalerDecisions:
    def test_scale_up_on_firing_saturation_alert(self):
        sink = _ListSink()
        spans_mod.enable()
        spans_mod.set_run_sink(sink)
        be, agg = _FakeBackend(size=1), _FakeAgg()
        d = _daemon(be, agg, max_fleet=4)
        assert d.decide_once(now=1000.0) is None  # healthy: no decision
        agg.fire("queue_depth_growth", seq=1)
        rec = d.decide_once(now=1001.0)
        assert rec is not None and rec["action"] == "up"
        assert be.spawned == 1 and be.size() == 2
        assert rec["rule"] == "queue_depth_growth"
        assert rec["transition_seq"] == 1
        assert rec["from"] == 1 and rec["to"] == 2
        assert rec["outcome"] == "spawned 1"
        assert rec["evidence"]  # ring tail attached
        # The record reached the telemetry sink and the decision ring.
        assert [r for r in sink.records if r.get("type") == "scale"]
        assert d.decisionz()["decisions"][-1] == rec
        # Metrics: counter + target gauge.
        snap = get_registry().snapshot()
        ups = [c for c in snap["counters"]
               if c["name"] == "autoscaler_decisions_total"]
        assert ups and ups[0]["labels"]["action"] == "up"
        assert get_registry().gauge("fleet_target_size").value == 2

    def test_cooldown_suppresses_consecutive_decisions(self):
        be, agg = _FakeBackend(size=1), _FakeAgg()
        d = _daemon(be, agg, max_fleet=8, cooldown_s=30.0)
        agg.fire("queue_depth_growth", seq=1)
        assert d.decide_once(now=1000.0) is not None
        agg.fire("queue_depth_growth", seq=2)  # even a fresh edge waits
        assert d.decide_once(now=1010.0) is None
        assert d.decide_once(now=1031.0) is not None  # cooldown elapsed
        assert be.spawned == 2

    def test_edge_only_mode_acts_once_per_transition(self):
        be, agg = _FakeBackend(size=1), _FakeAgg()
        d = _daemon(be, agg, max_fleet=8, repeat_while_firing=False)
        agg.fire("queue_depth_growth", seq=1)
        assert d.decide_once(now=1000.0) is not None
        # Still firing, same seq: no repeat even with cooldown over.
        assert d.decide_once(now=2000.0) is None
        # A fire→clear→fire cycle BETWEEN polls: seq jumped — a fresh
        # edge the poller never directly observed, still acted on.
        agg.fire("queue_depth_growth", seq=3)
        assert d.decide_once(now=3000.0) is not None
        assert be.spawned == 2

    def test_repeat_while_firing_steps_every_cooldown(self):
        be, agg = _FakeBackend(size=1), _FakeAgg()
        d = _daemon(be, agg, max_fleet=8, cooldown_s=10.0)
        agg.fire("queue_depth_growth", seq=1)
        for i, now in enumerate((1000.0, 1011.0, 1022.0)):
            assert d.decide_once(now=now) is not None, i
        assert be.size() == 4

    def test_max_fleet_clamp_is_not_a_decision(self):
        be, agg = _FakeBackend(size=3), _FakeAgg()
        d = _daemon(be, agg, max_fleet=3)
        agg.fire("queue_depth_growth", seq=1)
        assert d.decide_once(now=1000.0) is None
        assert be.spawned == 0 and d.decisionz()["total"] == 0

    def test_scale_down_on_idle_clamped_at_min(self):
        be, agg = _FakeBackend(size=3), _FakeAgg()
        d = _daemon(be, agg, min_fleet=2, max_fleet=8)
        agg.fire("worker_idle_ratio", seq=1, subject="w0", value=0.9)
        rec = d.decide_once(now=1000.0)
        assert rec is not None and rec["action"] == "down"
        assert be.drained == 1 and be.size() == 2
        # At min-fleet the next idle alert is a no-op, not a decision.
        agg.fire("worker_idle_ratio", seq=2, subject="w0", value=0.9)
        assert d.decide_once(now=2000.0) is None

    def test_saturation_beats_idleness(self):
        be, agg = _FakeBackend(size=2), _FakeAgg()
        d = _daemon(be, agg, max_fleet=8)
        agg.fire("queue_depth_growth", seq=1)
        agg.fire("worker_idle_ratio", seq=2, subject="w0")
        rec = d.decide_once(now=1000.0)
        assert rec["action"] == "up" and be.size() == 3

    def test_http_plane_serves_status_and_decisions(self):
        be, agg = _FakeBackend(size=1), _FakeAgg()
        d = AutoscalerDaemon(be, aggregator=agg, port=0, cooldown_s=0.0,
                             max_fleet=4, poll_interval=30.0)
        with d:
            agg.fire("queue_depth_growth", seq=1)
            assert d.decide_once(now=1000.0) is not None

            def get(path):
                with urllib.request.urlopen(d.url + path, timeout=5) as r:
                    return json.loads(r.read().decode())

            assert get("/healthz")["status"] == "ok"
            status = get("/statusz")
            assert status["config"]["max_fleet"] == 4
            assert status["backend"]["size"] == 2
            assert status["last_decision"]["action"] == "up"
            dz = get("/decisionz")
            assert dz["total"] == 1 and dz["decisions"][0]["rule"] == \
                "queue_depth_growth"

    def test_config_validation(self):
        be, agg = _FakeBackend(), _FakeAgg()
        with pytest.raises(ValueError):
            AutoscalerDaemon(be)  # no source
        with pytest.raises(ValueError):
            AutoscalerDaemon(be, aggregator=agg,
                             aggregator_url="http://x:1")  # two sources
        with pytest.raises(ValueError):
            _daemon(be, agg, min_fleet=5, max_fleet=2)
        with pytest.raises(ValueError):
            _daemon(be, agg, step=0)
