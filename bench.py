"""Benchmark: CIFAR-10 Genetic-CNN fitness throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Primary workload (fixed across rounds so BENCH_r{N}.json files are
comparable): BASELINE config #2's shape — S=(3, 4, 5), 20-individual
population, CIFAR-10-sized data (32×32×3, 10 classes; synthetic, since this
machine has no network to fetch real CIFAR — the compute is identical),
proxy-epoch fitness evaluation (kfold=2, 1 epoch/fold, batch 256, bfloat16)
exactly as the GA's batched population path runs it (models/cnn.py).

Metric: individuals evaluated / hour / chip, measured at steady state (the
one-off XLA compile is excluded; it amortizes over a 50-generation search,
and the mask-as-data design means it happens ONCE for the entire 8k+
architecture search space).

vs_baseline: the reference publishes no numbers (BASELINE.md); the only
quantitative anchor is the north star — 20×50 = 1000 evaluations on a
v5e-32 in < 2 h ⇒ 15.625 individuals/hour/chip.  vs_baseline = value / 15.625.

Additional evidence (VERDICT r1 item #2), reported as extra fields on the
same JSON line:

- ``full_schedule``: throughput at the REFERENCE-DEFAULT schedule —
  epochs=(20, 4, 1), lr=(1e-2, 1e-3, 1e-4), kfold=5 (SURVEY.md §3.4) — the
  number that answers "you only benchmarked the cheap config".  Gated by
  GENTUN_BENCH_FULL=0 for quick local runs (default ON).
- ``mfu``: analytic model-FLOPs utilisation for the full-schedule run.
  FLOPs are counted from the supergraph's conv/dense MACs only (the
  supergraph executes every node for every genome, so the analytic count IS
  the executed count; elementwise/pool/softmax FLOPs are excluded → the
  estimate is a lower bound).  Peak: 98.3e12 bf16 FLOP/s per TPU v5e chip
  (override with GENTUN_TPU_PEAK_FLOPS).
- ``accuracy``: mean val accuracy on the prototype-separable synthetic data
  for both configs, ASSERTED against regression bands set just under the
  measured round-2 values (proxy 0.632 → gate 0.5; full 0.9911 → gate 0.9)
  — a throughput win that halves accuracy now fails the bench instead of
  passing a loose sanity check (VERDICT r2 item 7).
- ``vs_prev_rounds``: throughput ratios and accuracy deltas against the
  recorded BENCH_r{N}.json files, so a throughput-up/accuracy-down trade is
  visible on the bench line itself.
"""

import json
import os
import time

import numpy as np

BASELINE_INDIVIDUALS_PER_HOUR_PER_CHIP = 1000 / 2.0 / 32  # north star, BASELINE.md

#: bf16 peak per TPU v5e ("v5 lite") chip; the MXU double-pumps bf16.
PEAK_FLOPS = float(os.environ.get("GENTUN_TPU_PEAK_FLOPS", 98.3e12))

NODES = (3, 4, 5)
FILTERS = (32, 64, 128)
INPUT_SHAPE = (32, 32, 3)
DENSE_UNITS = 256
N_CLASSES = 10
POP = 20
N_DATA = 10_000

COMMON = dict(
    nodes=NODES,
    kernels_per_layer=FILTERS,
    batch_size=256,
    dense_units=DENSE_UNITS,
    compute_dtype="bfloat16",
    seed=0,
)
PROXY = dict(COMMON, kfold=2, epochs=(1,), learning_rate=(0.01,))
# The reference-default fitness schedule (SURVEY.md §3.4): 25 epochs under a
# staged LR, 5-fold CV — 62.5× the proxy's epoch-fold budget.
FULL = dict(COMMON, kfold=5, epochs=(20, 4, 1), learning_rate=(1e-2, 1e-3, 1e-4))


def synthetic_cifar(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(10, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    x = protos[y] + 0.5 * rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    return x, y


def random_population(pop: int, seed: int):
    from gentun_tpu.genes import genetic_cnn_genome

    rng = np.random.default_rng(seed)
    spec = genetic_cnn_genome(NODES)
    return [spec.sample(rng) for _ in range(pop)]


def forward_flops_per_image() -> float:
    """Analytic forward MACs×2 for ONE image through the supergraph.

    The supergraph executes all K_s node convs per stage whatever the masks
    say (masks are data), so this is the executed count, not an average over
    genomes.  Convs dominate; pool/relu/mask elementwise ops are excluded.
    """
    h, w, c = INPUT_SHAPE
    flops = 0.0
    for k, f in zip(NODES, FILTERS):
        flops += 2.0 * h * w * 9 * c * f  # stage entry conv
        flops += k * 2.0 * h * w * 9 * f * f  # the k supergraph node convs
        h, w, c = h // 2, w // 2, f
    flops += 2.0 * (h * w * c) * DENSE_UNITS + 2.0 * DENSE_UNITS * N_CLASSES
    return flops


def schedule_flops(cfg: dict, pop: int) -> float:
    """Total executed conv/dense FLOPs for one cross_validate_population call."""
    from gentun_tpu.models.cnn import _eval_batch_size

    fwd = forward_flops_per_image()
    kfold = cfg["kfold"]
    batch = cfg["batch_size"]
    fold_size = N_DATA // kfold
    n_tr = N_DATA - fold_size
    steps_per_epoch = max(n_tr // batch, 1)
    total_steps = sum(cfg["epochs"]) * steps_per_epoch
    # mirror the model's actual eval padding (gentun_tpu.models.cnn)
    _, n_val_padded = _eval_batch_size(batch, fold_size)
    train = total_steps * batch * 3.0 * fwd  # bwd ≈ 2× fwd
    evalf = n_val_padded * fwd
    return pop * kfold * (train + evalf)


def prev_round_deltas(record: dict, base_dir: str | None = None) -> dict:
    """Throughput ratios / accuracy deltas vs each recorded BENCH_r{N}.json.

    Makes a throughput-up-accuracy-down trade visible on the bench line
    itself instead of requiring a manual diff of round artifacts.
    ``base_dir`` overrides where the artifacts are looked up (tests).
    """
    here = base_dir or os.path.dirname(os.path.abspath(__file__))
    out = {}
    for n in range(1, 100):
        path = os.path.join(here, f"BENCH_r{n:02d}.json")
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                prev = json.load(f).get("parsed") or {}
            entry = {}
            if prev.get("value"):
                entry["throughput_ratio"] = round(record["value"] / prev["value"], 3)
            prev_acc = (prev.get("accuracy") or {}).get("proxy_mean")
            if prev_acc is not None:
                entry["proxy_accuracy_delta"] = round(
                    record["accuracy"]["proxy_mean"] - prev_acc, 4
                )
            prev_full = prev.get("full_schedule") or {}
            cur_full = record.get("full_schedule") or {}
            if prev_full.get("individuals_per_hour_per_chip") and cur_full.get(
                "individuals_per_hour_per_chip"
            ):
                entry["full_throughput_ratio"] = round(
                    cur_full["individuals_per_hour_per_chip"]
                    / prev_full["individuals_per_hour_per_chip"],
                    3,
                )
            if prev_full.get("accuracy_mean") is not None and cur_full.get(
                "accuracy_mean"
            ) is not None:
                entry["full_accuracy_delta"] = round(
                    cur_full["accuracy_mean"] - prev_full["accuracy_mean"], 4
                )
            if entry:
                out[f"r{n:02d}"] = entry
        except (OSError, ValueError, KeyError):  # a malformed artifact never kills the bench
            continue
    return out


def timed_run(x, y, cfg: dict, pop: int):
    from gentun_tpu.models.cnn import GeneticCnnModel

    t0 = time.monotonic()
    accs = GeneticCnnModel.cross_validate_population(x, y, random_population(pop, seed=2), **cfg)
    return np.asarray(accs), time.monotonic() - t0


def main() -> None:
    x, y = synthetic_cifar(N_DATA)
    import jax

    n_chips = jax.local_device_count()

    # -- primary metric: proxy-schedule steady-state throughput ------------
    # Median of 3 measured repetitions: the tunneled chip shows ±20%
    # run-to-run wall-clock variance, and the median is what a search
    # actually sustains.
    timed_run(x, y, PROXY, POP)  # compile/cache warmup run
    reps = []
    for _ in range(3):
        proxy_accs, proxy_s = timed_run(x, y, PROXY, POP)
        reps.append(proxy_s)
    proxy_s = float(np.median(reps))
    value = POP / proxy_s * 3600.0 / n_chips
    assert np.isfinite(proxy_accs).all()
    chance = 1.0 / N_CLASSES
    # Regression band, not a sanity floor: round 2 measured 0.632 mean
    # proxy accuracy on this fixed workload; 0.5 is ~20% headroom for
    # run-to-run noise while still failing on any real learning regression.
    assert proxy_accs.mean() > 0.5, (
        f"proxy accuracy {proxy_accs.mean():.3f} regressed below the 0.5 gate "
        "(round-2 measured 0.632) — throughput is meaningless if the model "
        "stopped learning"
    )

    record = {
        "metric": "cifar10_individuals_per_hour_per_chip",
        "value": round(value, 2),
        "unit": "individuals/hour/chip",
        "vs_baseline": round(value / BASELINE_INDIVIDUALS_PER_HOUR_PER_CHIP, 3),
        "accuracy": {"proxy_mean": round(float(proxy_accs.mean()), 4), "chance": chance},
        "config": {"pop": POP, "schedule": "proxy kfold=2 epochs=(1,)"},
    }

    # -- full reference-default schedule + MFU (VERDICT r1 #2) -------------
    # The full run is 62.5× the proxy budget; a crash or failed assertion
    # there must not discard the already-measured primary metric, so it is
    # recorded as an error field on the same single JSON line instead.
    if os.environ.get("GENTUN_BENCH_FULL", "1") != "0":
        try:
            # One run, compile included: at this budget the compile is
            # noise, and a search pays it once per 1000 evaluations.
            full_accs, full_s = timed_run(x, y, FULL, POP)
            full_rate = POP / full_s * 3600.0 / n_chips
            mfu = schedule_flops(FULL, POP) / full_s / (PEAK_FLOPS * n_chips)
            assert np.isfinite(full_accs).all()
            # Round 2 measured 0.9911 at this schedule; 0.9 is the band.
            assert full_accs.mean() > 0.9, (
                f"full-schedule accuracy {full_accs.mean():.3f} regressed below "
                "the 0.9 gate (round-2 measured 0.9911)"
            )
            record["full_schedule"] = {
                "individuals_per_hour_per_chip": round(full_rate, 2),
                "vs_baseline": round(full_rate / BASELINE_INDIVIDUALS_PER_HOUR_PER_CHIP, 3),
                "wall_s": round(full_s, 1),
                "schedule": "kfold=5 epochs=(20,4,1) lr=(1e-2,1e-3,1e-4)",
                "accuracy_mean": round(float(full_accs.mean()), 4),
            }
            record["mfu"] = {
                "value": round(mfu, 4),
                "basis": "analytic conv+dense MACs (lower bound), full schedule",
                "peak_flops_per_chip": PEAK_FLOPS,
            }
        except Exception as e:  # loud but non-fatal: the proxy metric survives
            record["full_schedule"] = {"error": f"{type(e).__name__}: {e}"}
            # Strict mode (VERDICT r3 weak #6): the driver can opt into a
            # nonzero exit when the reference-default schedule crashes or
            # fails its accuracy gate, instead of relying on a human reading
            # the error field.  The record still prints first so the primary
            # metric is never lost.
            if os.environ.get("GENTUN_BENCH_STRICT") == "1":
                deltas = prev_round_deltas(record)
                if deltas:
                    record["vs_prev_rounds"] = deltas
                print(json.dumps(record))
                raise

    deltas = prev_round_deltas(record)
    if deltas:
        record["vs_prev_rounds"] = deltas
    print(json.dumps(record))


if __name__ == "__main__":
    main()
