"""Benchmark: CIFAR-10 Genetic-CNN fitness throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (fixed across rounds so BENCH_r{N}.json files are comparable):
BASELINE config #2's shape — S=(3, 4, 5), 20-individual population,
CIFAR-10-sized data (32×32×3, 10 classes; synthetic, since this machine has
no network to fetch real CIFAR — the compute is identical), proxy-epoch
fitness evaluation (kfold=2, 1 epoch/fold, batch 256, bfloat16) exactly as
the GA's batched population path runs it (models/cnn.py).

Metric: individuals evaluated / hour / chip, measured at steady state (the
one-off XLA compile is excluded; it amortizes over a 50-generation search,
and the mask-as-data design means it happens ONCE for the entire 8k+
architecture search space).

vs_baseline: the reference publishes no numbers (BASELINE.md); the only
quantitative anchor is the north star — 20×50 = 1000 evaluations on a
v5e-32 in < 2 h ⇒ 15.625 individuals/hour/chip.  vs_baseline = value / 15.625.
"""

import json
import time

import numpy as np

BASELINE_INDIVIDUALS_PER_HOUR_PER_CHIP = 1000 / 2.0 / 32  # north star, BASELINE.md


def synthetic_cifar(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    protos = rng.normal(size=(10, 32, 32, 3)).astype(np.float32)
    y = rng.integers(0, 10, size=n).astype(np.int32)
    x = protos[y] + 0.5 * rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
    return x, y


def random_population(pop: int, seed: int):
    from gentun_tpu.genes import genetic_cnn_genome

    rng = np.random.default_rng(seed)
    spec = genetic_cnn_genome((3, 4, 5))
    return [spec.sample(rng) for _ in range(pop)]


def main() -> None:
    from gentun_tpu.models.cnn import GeneticCnnModel

    pop = 20
    config = dict(
        nodes=(3, 4, 5),
        kernels_per_layer=(32, 64, 128),
        kfold=2,
        epochs=(1,),
        learning_rate=(0.01,),
        batch_size=256,
        dense_units=256,
        compute_dtype="bfloat16",
        seed=0,
    )
    x, y = synthetic_cifar(10_000)

    # Warmup: same shapes/config → compiles and caches the one program.
    GeneticCnnModel.cross_validate_population(x, y, random_population(pop, seed=1), **config)

    t0 = time.monotonic()
    accs = GeneticCnnModel.cross_validate_population(x, y, random_population(pop, seed=2), **config)
    elapsed = time.monotonic() - t0

    import jax

    n_chips = jax.local_device_count()
    value = pop / elapsed * 3600.0 / n_chips
    assert np.isfinite(accs).all()
    print(
        json.dumps(
            {
                "metric": "cifar10_individuals_per_hour_per_chip",
                "value": round(value, 2),
                "unit": "individuals/hour/chip",
                "vs_baseline": round(value / BASELINE_INDIVIDUALS_PER_HOUR_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
